"""Real-TPU canary for the party-sharded tiled engine's vma checking.

History (docs/KNOWN_ISSUES.md KI-1): round 4 shipped the flagship
multi-device path (packet-tiled kernels under ``shard_map``) with
``check_vma=False`` after a Mosaic ``pvary`` lowering failure, leaving
its semantics pinned only by CPU-mesh equivalence tests.  Round 5 found
the failure gone once the kernels' ``out_vma`` is actually declared
(round 4 hard-coded ``None``), and the checker is now ON by default on
TPU.  This canary re-validates all three configurations on hardware so
a toolchain regression is caught loudly, not silently:

1. **Checker-ON control** — the grid-less monolithic Pallas engine with
   ``check_vma=True`` (the configuration that always worked).
2. **Tiled, checker force-OFF** (``QBA_TILED_CHECK_VMA=0``, the escape
   hatch) — must stay bit-identical to the single-device tiled engine.
3. **Tiled, default (checker ON on TPU)** — must compile, run, and stay
   bit-identical.  If THIS step fails with a Mosaic lowering error, the
   toolchain has regressed: re-open KI-1 and ship
   ``QBA_TILED_CHECK_VMA=0`` as the default until fixed.

Run:  python examples/tpu_vma_canary.py        (needs a real TPU)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _require_tpu():
    if jax.default_backend() != "tpu":
        print("SKIP: no TPU backend (this canary is hardware-only)")
        sys.exit(0)


def _cfg(engine):
    from qba_tpu.config import QBAConfig

    return QBAConfig(
        n_parties=5, size_l=16, n_dishonest=2, trials=4,
        round_engine=engine, seed=9,
    )


def _tiled_spmd_vs_single(label):
    from qba_tpu.backends.jax_backend import run_trials
    from qba_tpu.parallel.mesh import make_mesh
    from qba_tpu.parallel.spmd import run_trials_spmd

    cfg = _cfg("pallas_tiled")
    mesh = make_mesh({"tp": 1}, devices=jax.devices()[:1])
    spmd_out = run_trials_spmd(cfg, mesh)
    single = run_trials(cfg)
    a = np.asarray(spmd_out.trials.success)
    b = np.asarray(single.trials.success)
    assert (a == b).all(), (a, b)
    av = np.asarray(spmd_out.trials.decisions)
    bv = np.asarray(single.trials.decisions)
    assert (av == bv).all(), "decision mismatch spmd vs single-device"
    print(f"{label}: OK (bit-identical to single-device)")


def step_control_monolithic():
    from qba_tpu.parallel.mesh import make_mesh
    from qba_tpu.parallel.spmd import run_trials_spmd

    mesh = make_mesh({"tp": 1}, devices=jax.devices()[:1])
    out = run_trials_spmd(_cfg("pallas"), mesh)
    print("1. monolithic checker-ON tp=1: OK",
          np.asarray(out.trials.success).tolist())


def step_tiled_checker_off():
    os.environ["QBA_TILED_CHECK_VMA"] = "0"
    try:
        _tiled_spmd_vs_single("2. tiled checker-OFF tp=1 (escape hatch)")
    finally:
        del os.environ["QBA_TILED_CHECK_VMA"]


def step_tiled_default_checker_on():
    try:
        _tiled_spmd_vs_single("3. tiled DEFAULT (checker ON) tp=1")
    except Exception as e:
        print(
            "3. tiled DEFAULT (checker ON) tp=1: FAILED — the toolchain "
            "has regressed on vma-tracked grid'd kernels.  Re-open "
            "docs/KNOWN_ISSUES.md KI-1 and default QBA_TILED_CHECK_VMA "
            f"to 0 in qba_tpu/parallel/spmd.py.\n   {type(e).__name__}: "
            f"{str(e)[:600]}"
        )
        sys.exit(1)


if __name__ == "__main__":
    _require_tpu()
    step_control_monolithic()
    step_tiled_checker_off()
    step_tiled_default_checker_on()
    print("canary: all configurations healthy")
