"""Load generator for the evaluation service (docs/SERVING.md).

Replays a mixed-shape request stream (three (n, sizeL, d) buckets,
interleaved, varied seeds and trial counts) against a `qba-tpu serve`
process over the file-queue transport, then reports:

* sustained throughput (requests/min, end to end across the stream),
* p50/p99 latency computed from the returned span data — each result's
  ``latency_s`` is the duration of that request's ``request`` span, so
  the summary here reproduces the server's own span-derived numbers,
* manifest validation (every result must carry a schema-clean run
  manifest), and
* a bit-identity spot check: one request per bucket re-run directly
  through the engine must match the served result trial for trial.

With ``--transport socket`` the stream instead goes through the fleet
stack (docs/SERVING.md "Fleet"): a socket front-end with target-aware
admission feeding ``--replicas N`` worker processes over one shared
queue, with per-replica attribution in the report and a
``fleet_summary.json`` in the queue dir.

Chaos modes (each asserts zero lost requests + bounded blast radius):

* ``--chaos-kill`` — SIGKILL one replica mid-stream; the survivors
  reclaim the victim's in-flight claims (no supervisor needed).
* ``--chaos-hang`` — SIGSTOP one replica mid-stream (``--supervise``
  required): the watchdog must classify it hung, SIGKILL it, and
  release its in-flight request for a survivor within one watchdog
  period.
* ``--chaos-poison`` — inject a request that hard-crashes any worker
  claiming it (the transport's test-only ``QBA_TEST_CRASH_HOOK``;
  ``--supervise`` required): the supervisor must quarantine it after
  at most 2 worker deaths, return a crash-report error for it, and
  serve every other request cleanly.
* ``--chaos-flap`` — SIGKILL the same replica repeatedly
  (``--supervise`` required): the crash-loop breaker must bench the
  slot and release its admission capacity while the survivor finishes
  the stream.

Usage:
    python examples/load_gen.py                     # subprocess server
    python examples/load_gen.py --in-process        # same, no subprocess
    python examples/load_gen.py --requests 60 --chunk-trials 16
    python examples/load_gen.py --transport socket --replicas 2
    python examples/load_gen.py --transport socket --replicas 2 --chaos-kill
    python examples/load_gen.py --transport socket --replicas 2 \\
        --supervise --chaos-poison
"""

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time
import types

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Three shape buckets: small-cheap, wider party count, longer sizeL.
BUCKETS = (
    dict(n_parties=4, size_l=8, n_dishonest=1),
    dict(n_parties=5, size_l=8, n_dishonest=1),
    dict(n_parties=4, size_l=16, n_dishonest=2),
)

#: --chaos-poison marker: workers spawned with QBA_TEST_CRASH_HOOK set
#: to this token hard-exit when they claim a request whose id contains
#: it (qba_tpu.serve.transport.CRASH_HOOK_ENV).
POISON_TOKEN = "poisonpill"


def make_stream(n_requests: int, trials: int, target: str | None = None):
    from qba_tpu.serve import EvalRequest

    return [
        EvalRequest(
            request_id=f"lg{i:04d}",
            trials=trials + (i % 3),  # varied sizes exercise chunk packing
            seed=17 * i + 1,
            target=target,
            **BUCKETS[i % len(BUCKETS)],
        )
        for i in range(n_requests)
    ]


def run_in_process(args, stream):
    from qba_tpu.serve import QBAServer, serve_batch

    server = QBAServer(
        chunk_trials=args.chunk_trials,
        telemetry_dir=args.telemetry,
        cache_dir=args.cache_dir,
    )
    t0 = time.perf_counter()
    results = [r.to_json() for r in serve_batch(server, stream)]
    return results, time.perf_counter() - t0


def run_subprocess(args, stream):
    queue_dir = args.queue_dir or tempfile.mkdtemp(prefix="qba_serve_")
    inbox = os.path.join(queue_dir, "inbox")
    outbox = os.path.join(queue_dir, "outbox")
    os.makedirs(inbox, exist_ok=True)
    cmd = [
        sys.executable, "-m", "qba_tpu", "serve",
        "--transport", "file-queue", "--queue-dir", queue_dir,
        "--chunk-trials", str(args.chunk_trials),
    ]
    if args.telemetry:
        cmd += ["--telemetry", args.telemetry]
    if args.cache_dir:
        cmd += ["--cache-dir", args.cache_dir]
    proc = subprocess.Popen(cmd)
    try:
        t0 = time.perf_counter()
        for req in stream:
            # Temp-file + rename so the server never reads partial JSON.
            tmp = os.path.join(inbox, f".{req.request_id}.tmp")
            with open(tmp, "w") as f:
                json.dump(req.to_json(), f)
            os.replace(tmp, os.path.join(inbox, req.request_id + ".json"))
        deadline = time.time() + args.timeout_s
        while time.time() < deadline:
            done = os.listdir(outbox) if os.path.isdir(outbox) else []
            if len(done) >= len(stream):
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server exited early (rc={proc.returncode})")
            time.sleep(0.05)
        else:
            raise RuntimeError(
                f"timed out: {len(os.listdir(outbox))}/{len(stream)} results"
            )
        elapsed = time.perf_counter() - t0
    finally:
        open(os.path.join(queue_dir, "stop"), "w").close()
        proc.wait(timeout=120)
    results = []
    for name in sorted(os.listdir(outbox)):
        with open(os.path.join(outbox, name)) as f:
            results.append(json.load(f))
    return results, elapsed


def _mid_stream(frontend, stream, timeout_s):
    """Block until the fleet is mid-stream (a quarter of the results
    forwarded) — counted via the front-end, not the outbox listing: it
    moves forwarded results to consumed/ as they land."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if frontend.results_forwarded >= max(1, len(stream) // 4):
            return
        time.sleep(0.05)


def run_socket(args, stream):
    """Drive the full fleet stack: socket front-end + admission +
    ``--replicas`` worker processes on one shared queue dir, optionally
    supervised, optionally under chaos."""
    import signal as signallib
    import socket as socketlib
    import threading

    from qba_tpu.serve.fleet import (
        AdmissionController,
        FleetFrontend,
        FleetSupervisor,
        ReplicaPool,
        fleet_summary,
        write_fleet_summary,
    )
    from qba_tpu.serve.transport import CRASH_HOOK_ENV

    chaos = [
        f for f in ("chaos_kill", "chaos_hang", "chaos_poison", "chaos_flap")
        if getattr(args, f)
    ]
    if len(chaos) > 1:
        raise SystemExit(f"pick one chaos mode, not {chaos}")
    if chaos and args.replicas < 2:
        raise SystemExit(f"--{chaos[0].replace('_', '-')} needs "
                         "--replicas >= 2 (a survivor must finish the "
                         "stream)")
    if chaos and chaos[0] != "chaos_kill" and not args.supervise:
        raise SystemExit(f"--{chaos[0].replace('_', '-')} needs "
                         "--supervise (the supervisor IS the recovery "
                         "path under test)")
    queue_dir = args.queue_dir or tempfile.mkdtemp(prefix="qba_fleet_")
    admission = AdmissionController(
        chunk_trials=args.chunk_trials, replicas=args.replicas
    )
    pool = ReplicaPool(
        queue_dir,
        replicas=args.replicas,
        chunk_trials=args.chunk_trials,
        cache_dir=args.cache_dir,
        telemetry_dir=args.telemetry,
        reclaim_timeout_s=args.reclaim_timeout_s,
        poll_s=0.02,
        respawn_backoff_s=0.2,
    )
    supervisor = None
    sup_stop = threading.Event()
    sup_thread = None
    if args.supervise:
        supervisor = FleetSupervisor(
            pool,
            admission=admission,
            watchdog_s=args.watchdog_s,
            breaker_k=3,
            breaker_window_s=60.0,
            poison_threshold=2,
        )
    frontend = FleetFrontend(
        queue_dir,
        admission,
        max_requests=len(stream),
        health_provider=supervisor.health if supervisor else None,
    )
    if args.chaos_poison:
        # Workers inherit the environment at spawn: arm the test-only
        # crash hook so claiming a poison-marked request kills them.
        # Stays set until the run ends — the supervisor RESPAWNS dead
        # workers mid-stream, and a respawn must be just as mortal.
        os.environ[CRASH_HOOK_ENV] = POISON_TOKEN
    pool.start()
    if supervisor is not None:
        sup_thread = threading.Thread(
            target=supervisor.run, args=(sup_stop, 0.2), daemon=True
        )
        sup_thread.start()
    t0 = time.perf_counter()
    results = []
    try:
        port = frontend.start_in_thread()
        sock = socketlib.create_connection(
            ("127.0.0.1", port), timeout=args.timeout_s
        )
        wire = sock.makefile("rw")
        for req in stream:
            wire.write(json.dumps(req.to_json()) + "\n")
        wire.flush()
        sock.shutdown(socketlib.SHUT_WR)
        if args.chaos_kill:
            # SIGKILL one replica mid-stream; its unclaimed + in-flight
            # work must be reclaimed by the survivors (zero lost
            # requests, asserted in main).
            _mid_stream(frontend, stream, args.timeout_s)
            victim = pool.alive()[-1]
            pid = pool.kill(victim)
            print(f"chaos: SIGKILL replica {victim} (pid {pid}); "
                  f"survivors {pool.alive()} reclaim its claims")
        elif args.chaos_hang:
            # SIGSTOP one replica mid-stream: it stays "alive" to the
            # pool but its heartbeat goes stale — only the supervisor's
            # watchdog can tell it from a busy worker.
            _mid_stream(frontend, stream, args.timeout_s)
            victim = next(
                r for r in pool.replicas if r.replica_id == pool.alive()[-1]
            )
            os.kill(victim.proc.pid, signallib.SIGSTOP)
            print(f"chaos: SIGSTOP replica {victim.replica_id} "
                  f"(pid {victim.proc.pid}); the watchdog must kill it "
                  "and re-serve its in-flight request")
        elif args.chaos_flap:
            # Kill the same slot repeatedly: the crash-loop breaker
            # must bench it instead of respawning forever.
            _mid_stream(frontend, stream, args.timeout_s)
            victim = pool.alive()[-1]
            deadline = time.time() + args.timeout_s
            for k in range(3):
                while time.time() < deadline:
                    try:
                        pool.kill(victim)
                        break
                    except ValueError:
                        time.sleep(0.1)  # waiting on the respawn
                print(f"chaos: SIGKILL {k + 1}/3 of replica {victim}")
            print(f"breaker should bench {victim}; survivors "
                  "finish the stream")
        for line in wire:
            if line.strip():
                results.append(json.loads(line))
        elapsed = time.perf_counter() - t0
        if supervisor is not None and (args.chaos_hang or args.chaos_flap):
            # Fast survivors can drain the whole stream before the
            # frozen victim's beat goes stale (hang) or before the
            # supervisor's next poll sees the last death (flap).  The
            # detection itself is the contract under test, so hold the
            # supervisor open until it lands instead of racing it to
            # shutdown.
            settle = time.time() + max(30.0, 4 * args.watchdog_s)
            while time.time() < settle:
                if args.chaos_hang and supervisor.hung_killed:
                    break
                if args.chaos_flap and pool.benched:
                    break
                time.sleep(0.2)
        # Final metrics scrape: the same text ``GET /metrics`` serves
        # (one render path), taken before teardown so the report embeds
        # the end-of-stream counter state.  Collectors still see the
        # live queue dir here.
        from qba_tpu.obs.metrics import validate_exposition

        args._metrics_text = frontend.metrics.render()
        args._metrics_errors = validate_exposition(args._metrics_text)
    finally:
        os.environ.pop(CRASH_HOOK_ENV, None)
        frontend.stop_in_thread()
        sup_stop.set()
        if sup_thread is not None:
            sup_thread.join(timeout=30)
        codes = pool.stop()
    summary = fleet_summary(
        queue_dir,
        admission_summary=admission.summary(),
        frontend_status=frontend.status(),
        elapsed_s=elapsed,
        telemetry_dir=args.telemetry,
        self_healing=supervisor.summary() if supervisor else None,
    )
    summary["replica_exit_codes"] = codes
    path = write_fleet_summary(queue_dir, summary)
    print(f"fleet summary:   {path}")
    args._fleet_summary = summary  # chaos assertions in main()
    return results, elapsed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=21)
    ap.add_argument("--trials", type=int, default=6, help="trials per request (base)")
    ap.add_argument("--chunk-trials", type=int, default=8)
    ap.add_argument("--in-process", action="store_true",
                    help="drive QBAServer directly instead of a subprocess")
    ap.add_argument("--transport", choices=("file-queue", "socket"),
                    default="file-queue",
                    help="file-queue = one subprocess server; socket = the "
                    "fleet stack (front-end + admission + --replicas "
                    "workers)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="worker processes for --transport socket")
    ap.add_argument("--chaos-kill", action="store_true",
                    help="socket transport only: SIGKILL one replica "
                    "mid-stream and assert zero lost requests")
    ap.add_argument("--supervise", action="store_true",
                    help="socket transport only: run the self-healing "
                    "supervisor (watchdog + quarantine + breaker)")
    ap.add_argument("--watchdog-s", type=float, default=3.0,
                    help="supervisor heartbeat staleness budget "
                    "(compile phase gets 30x)")
    ap.add_argument("--chaos-hang", action="store_true",
                    help="SIGSTOP one replica mid-stream; needs "
                    "--supervise: the watchdog must detect + recover")
    ap.add_argument("--chaos-poison", action="store_true",
                    help="inject a worker-crashing request; needs "
                    "--supervise: quarantined after <= 2 deaths with a "
                    "crash report, everything else served cleanly")
    ap.add_argument("--chaos-flap", action="store_true",
                    help="SIGKILL the same replica 3x; needs "
                    "--supervise: the breaker must bench the slot and "
                    "release its admission capacity")
    ap.add_argument("--reclaim-timeout-s", type=float, default=30.0,
                    help="fleet crash-recovery reclaim timeout; must "
                    "exceed the worst-case claim-to-result time (cold "
                    "compiles!) or live claims get double-served")
    ap.add_argument("--report-json", default=None,
                    help="write {rpm, p50_s, p99_s, results, replicas} "
                    "to this file (CI compares 1- vs 2-replica rates); "
                    "socket transport also embeds the final /metrics "
                    "scrape and the stitched-trace summary")
    ap.add_argument("--queue-dir", default=None)
    ap.add_argument("--telemetry", default=None,
                    help="per-request manifest/trace directory")
    ap.add_argument("--cache-dir", default=None,
                    help="warm-start artifact directory")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--target", default=None,
                    help="precision target applied to every request "
                    "(qba_tpu.stats.parse_target grammar, e.g. "
                    "'decide vs 1/3 @ 95%%'); --trials becomes the "
                    "budget ceiling and requests finish early once "
                    "their stopping rule resolves")
    ap.add_argument("--min-early-stop", type=int, default=0,
                    help="fail unless at least this many targeted "
                    "requests stopped before exhausting their budget "
                    "(the CI stats job asserts the early-stop path "
                    "actually exercised)")
    args = ap.parse_args(argv)

    stream = make_stream(args.requests, args.trials, target=args.target)
    poison_ids = set()
    if args.chaos_poison:
        from qba_tpu.serve import EvalRequest

        # One poison request mid-stream (past the bit-identity head):
        # any worker that claims it dies via the test-only crash hook.
        poison = EvalRequest(
            request_id=f"lg-{POISON_TOKEN}", trials=4, seed=999, **BUCKETS[0]
        )
        stream.insert(len(stream) // 2, poison)
        poison_ids = {poison.request_id}
    if args.in_process:
        results, elapsed = run_in_process(args, stream)
    elif args.transport == "socket":
        results, elapsed = run_socket(args, stream)
    else:
        results, elapsed = run_subprocess(args, stream)

    errors = [r for r in results if r.get("error")]
    unexpected = [r for r in errors if r["request_id"] not in poison_ids]
    if unexpected:
        raise SystemExit(
            f"{len(unexpected)} requests failed: {unexpected[:3]}"
        )
    if len(results) != len(stream):
        raise SystemExit(f"got {len(results)} results for {len(stream)} requests")

    # Every served result must carry a schema-clean manifest (poison
    # requests never execute — their crash-report errors have none).
    from qba_tpu.obs.manifest import validate_manifest

    for r in results:
        if r["request_id"] not in poison_ids:
            validate_manifest(r["manifest"])

    # Bit-identity spot check: first request of each bucket vs a direct
    # engine run of the identical config.
    from qba_tpu.backends.jax_backend import run_trials, trial_keys

    by_id = {r["request_id"]: r for r in results}
    for req in stream[: len(BUCKETS)]:
        direct = run_trials(req.config(), trial_keys(req.config()))
        import numpy as np

        want = [bool(x) for x in np.asarray(direct.trials.success)]
        got = by_id[req.request_id]["success"]
        # Targeted requests may stop early; the served trials must then
        # be a bit-identical *prefix* of the direct fixed-budget run
        # (chunk keys are a pure function of seed + chunk index).
        if got != want[: len(got)] or (args.target is None and len(got) != len(want)):
            raise SystemExit(f"bit-identity violation on {req.request_id}")

    # p50/p99 from the returned span data: latency_s IS each request's
    # span duration, so feed them back through the span summarizer.
    from qba_tpu.obs.telemetry import span_latency_summary

    spans = [
        types.SimpleNamespace(name="request", dur=r["latency_s"])
        for r in results
        if not r.get("error")  # a quarantined request has no latency
    ]
    lat = span_latency_summary(spans, "request")
    rpm = len(results) / elapsed * 60.0
    print(f"requests:        {len(results)} across {len(BUCKETS)} buckets")
    print(f"wall time:       {elapsed:.2f} s")
    print(f"sustained rate:  {rpm:.1f} requests/min")
    print(f"latency p50:     {lat['p50_s'] * 1e3:.1f} ms")
    print(f"latency p99:     {lat['p99_s'] * 1e3:.1f} ms")
    print(f"latency mean:    {lat['mean_s'] * 1e3:.1f} ms  "
          f"(min {lat['min_s'] * 1e3:.1f}, max {lat['max_s'] * 1e3:.1f})")

    if args.transport == "socket":
        # Per-replica attribution: every result names the replica that
        # served it, and queue-wait vs device-time come from its spans.
        per = {}
        for r in results:
            per.setdefault(r.get("replica_id"), []).append(r)
        for rid in sorted(per, key=str):
            rs = per[rid]
            waits = [r["queue_wait_s"] for r in rs
                     if r.get("queue_wait_s") is not None]
            mean_wait = sum(waits) / len(waits) * 1e3 if waits else 0.0
            mean_dev = sum(r["latency_s"] for r in rs) / len(rs) * 1e3
            print(f"replica {rid}:      {len(rs)} requests, "
                  f"mean queue-wait {mean_wait:.1f} ms, "
                  f"mean device-time {mean_dev:.1f} ms")
        admitted = [r for r in results
                    if (r.get("admission") or {}).get("action")]
        if admitted:
            print(f"admission:       {len(admitted)}/{len(results)} "
                  "results carry a typed admission decision")

        # Metrics plane: the final scrape must be valid Prometheus
        # text exposition — an invalid page means every dashboard on
        # it silently flatlines, so fail the run here.
        exposition_errors = getattr(args, "_metrics_errors", None)
        if exposition_errors:
            raise SystemExit(
                f"/metrics exposition invalid: {exposition_errors[:3]}"
            )
        if getattr(args, "_metrics_text", None):
            n_samples = sum(
                1 for line in args._metrics_text.splitlines()
                if line and not line.startswith("#")
            )
            print(f"metrics:         {n_samples} samples, "
                  "exposition valid")

        # Tracing plane: every request resolved one stitched trace,
        # and no worker span is orphaned from its intake.
        traces = (getattr(args, "_fleet_summary", None) or {}).get("traces")
        if traces:
            if traces["orphan_spans"]:
                raise SystemExit(
                    f"{traces['orphan_spans']} orphan worker span(s): "
                    "trace context was dropped between intake and worker"
                )
            cov = traces.get("coverage") or {}
            print(f"traces:          {traces['count']} stitched "
                  f"({traces['closed']} closed, 0 orphan spans"
                  + (f", coverage p50 {cov['p50']:.0%}" if cov else "")
                  + ")")

        # Chaos postconditions: bounded blast radius, proven from the
        # fleet summary + the crash reports on the wire (KI-9).
        fleet = getattr(args, "_fleet_summary", None) or {}
        healing = fleet.get("self_healing") or {}
        if args.chaos_poison:
            poisoned = [r for r in errors if r["request_id"] in poison_ids]
            if len(poisoned) != len(poison_ids):
                raise SystemExit(
                    f"poison requests got {len(poisoned)} error results, "
                    f"expected {len(poison_ids)}"
                )
            for r in poisoned:
                report = r.get("crash_report")
                if not report:
                    raise SystemExit(
                        f"poison result {r['request_id']} carries no "
                        f"crash report: {r.get('error')}"
                    )
                missing = {"blamed_replicas", "phases", "exit_codes",
                           "reclaim_count"} - set(report)
                if missing:
                    raise SystemExit(f"crash report missing {missing}")
                if len(report["blamed_replicas"]) > 2:
                    raise SystemExit(
                        "blast radius exceeded: poison request killed "
                        f"{len(report['blamed_replicas'])} workers "
                        "(quarantine threshold is 2)"
                    )
            if fleet.get("quarantined", 0) < len(poison_ids):
                raise SystemExit(
                    "fleet summary missed the quarantine: "
                    f"{fleet.get('quarantined')} < {len(poison_ids)}"
                )
            print(f"chaos-poison:    quarantined after "
                  f"{len(poisoned[0]['crash_report']['blamed_replicas'])} "
                  "worker death(s); crash report on the wire")
        if args.chaos_hang:
            if healing.get("hung_killed", 0) < 1:
                raise SystemExit(
                    "the watchdog never killed the SIGSTOP'd replica "
                    f"(self_healing: {healing})"
                )
            print(f"chaos-hang:      watchdog killed "
                  f"{healing['hung_killed']} hung worker(s); "
                  "stream completed with zero lost requests")
        if args.chaos_flap:
            benched = healing.get("benched") or []
            adm = fleet.get("admission") or {}
            if not benched:
                raise SystemExit(
                    f"the breaker never benched the flapping replica "
                    f"(self_healing: {healing})"
                )
            if adm and adm.get("capacity_trials", 0) >= adm.get(
                "base_capacity_trials", 0
            ):
                raise SystemExit(
                    "benched replica did not release admission capacity: "
                    f"{adm.get('capacity_trials')} >= "
                    f"{adm.get('base_capacity_trials')}"
                )
            print(f"chaos-flap:      breaker benched {benched}; "
                  f"admission window now {adm.get('capacity_trials')}"
                  f"/{adm.get('base_capacity_trials')} trials")

    if args.target:
        # Time-to-decision: for a targeted request the request span
        # closes when its stopping rule resolves (or the budget runs
        # out), so the same span durations ARE the decision latencies —
        # summarize the decided subset separately from the full stream.
        decided = [
            r for r in results
            if r.get("stop") and r["stop"]["reason"] != "budget_exhausted"
        ]
        # "Early" = decided with trials to spare in the budget.
        early = [
            r for r in decided
            if r["n_trials"]
            < next(q.trials for q in stream if q.request_id == r["request_id"])
        ]
        if decided:
            dspans = [
                types.SimpleNamespace(name="decision", dur=r["latency_s"])
                for r in decided
            ]
            dlat = span_latency_summary(dspans, "decision")
            saved = sum(
                next(q.trials for q in stream
                     if q.request_id == r["request_id"]) - r["n_trials"]
                for r in decided
            )
            print(f"target:          {args.target!r}")
            print(f"decided:         {len(decided)}/{len(results)} "
                  f"({len(early)} early, {saved} budget trials saved)")
            print(f"time-to-decision p50: {dlat['p50_s'] * 1e3:.1f} ms  "
                  f"p99: {dlat['p99_s'] * 1e3:.1f} ms")
        else:
            print(f"target:          {args.target!r} (no request decided "
                  "within budget)")
        if len(early) < args.min_early_stop:
            raise SystemExit(
                f"only {len(early)} requests early-stopped "
                f"(--min-early-stop {args.min_early_stop}): the "
                "precision-target path was not exercised"
            )

    print("manifests:       all valid; bit-identity spot check passed")
    if args.report_json:
        with open(args.report_json, "w") as f:
            json.dump(
                {
                    "rpm": rpm,
                    "p50_s": lat["p50_s"],
                    "p99_s": lat["p99_s"],
                    "results": len(results),
                    "transport": args.transport,
                    "replicas": (
                        args.replicas if args.transport == "socket" else 1
                    ),
                    "chaos_kill": bool(args.chaos_kill),
                    "chaos": [
                        m for m in ("kill", "hang", "poison", "flap")
                        if getattr(args, f"chaos_{m}")
                    ],
                    "supervised": bool(args.supervise),
                    "self_healing": (
                        getattr(args, "_fleet_summary", None) or {}
                    ).get("self_healing"),
                    "served_by": sorted(
                        {str(r.get("replica_id")) for r in results}
                    ),
                    # Final /metrics scrape (socket transport): the
                    # Prometheus page as served, plus any exposition
                    # errors (empty list = valid page).
                    "metrics": getattr(args, "_metrics_text", None),
                    "metrics_exposition_errors": getattr(
                        args, "_metrics_errors", None
                    ),
                    "traces": (
                        getattr(args, "_fleet_summary", None) or {}
                    ).get("traces"),
                },
                f,
                indent=1,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
