"""Load generator for the evaluation service (docs/SERVING.md).

Replays a mixed-shape request stream (three (n, sizeL, d) buckets,
interleaved, varied seeds and trial counts) against a `qba-tpu serve`
process over the file-queue transport, then reports:

* sustained throughput (requests/min, end to end across the stream),
* p50/p99 latency computed from the returned span data — each result's
  ``latency_s`` is the duration of that request's ``request`` span, so
  the summary here reproduces the server's own span-derived numbers,
* manifest validation (every result must carry a schema-clean run
  manifest), and
* a bit-identity spot check: one request per bucket re-run directly
  through the engine must match the served result trial for trial.

With ``--transport socket`` the stream instead goes through the fleet
stack (docs/SERVING.md "Fleet"): a socket front-end with target-aware
admission feeding ``--replicas N`` worker processes over one shared
queue, with per-replica attribution in the report and a
``fleet_summary.json`` in the queue dir.  ``--chaos-kill`` additionally
SIGKILLs one replica mid-stream and asserts zero lost requests — the
survivors reclaim the victim's in-flight claims.

Usage:
    python examples/load_gen.py                     # subprocess server
    python examples/load_gen.py --in-process        # same, no subprocess
    python examples/load_gen.py --requests 60 --chunk-trials 16
    python examples/load_gen.py --transport socket --replicas 2
    python examples/load_gen.py --transport socket --replicas 2 --chaos-kill
"""

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time
import types

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Three shape buckets: small-cheap, wider party count, longer sizeL.
BUCKETS = (
    dict(n_parties=4, size_l=8, n_dishonest=1),
    dict(n_parties=5, size_l=8, n_dishonest=1),
    dict(n_parties=4, size_l=16, n_dishonest=2),
)


def make_stream(n_requests: int, trials: int, target: str | None = None):
    from qba_tpu.serve import EvalRequest

    return [
        EvalRequest(
            request_id=f"lg{i:04d}",
            trials=trials + (i % 3),  # varied sizes exercise chunk packing
            seed=17 * i + 1,
            target=target,
            **BUCKETS[i % len(BUCKETS)],
        )
        for i in range(n_requests)
    ]


def run_in_process(args, stream):
    from qba_tpu.serve import QBAServer, serve_batch

    server = QBAServer(
        chunk_trials=args.chunk_trials,
        telemetry_dir=args.telemetry,
        cache_dir=args.cache_dir,
    )
    t0 = time.perf_counter()
    results = [r.to_json() for r in serve_batch(server, stream)]
    return results, time.perf_counter() - t0


def run_subprocess(args, stream):
    queue_dir = args.queue_dir or tempfile.mkdtemp(prefix="qba_serve_")
    inbox = os.path.join(queue_dir, "inbox")
    outbox = os.path.join(queue_dir, "outbox")
    os.makedirs(inbox, exist_ok=True)
    cmd = [
        sys.executable, "-m", "qba_tpu", "serve",
        "--transport", "file-queue", "--queue-dir", queue_dir,
        "--chunk-trials", str(args.chunk_trials),
    ]
    if args.telemetry:
        cmd += ["--telemetry", args.telemetry]
    if args.cache_dir:
        cmd += ["--cache-dir", args.cache_dir]
    proc = subprocess.Popen(cmd)
    try:
        t0 = time.perf_counter()
        for req in stream:
            # Temp-file + rename so the server never reads partial JSON.
            tmp = os.path.join(inbox, f".{req.request_id}.tmp")
            with open(tmp, "w") as f:
                json.dump(req.to_json(), f)
            os.replace(tmp, os.path.join(inbox, req.request_id + ".json"))
        deadline = time.time() + args.timeout_s
        while time.time() < deadline:
            done = os.listdir(outbox) if os.path.isdir(outbox) else []
            if len(done) >= len(stream):
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server exited early (rc={proc.returncode})")
            time.sleep(0.05)
        else:
            raise RuntimeError(
                f"timed out: {len(os.listdir(outbox))}/{len(stream)} results"
            )
        elapsed = time.perf_counter() - t0
    finally:
        open(os.path.join(queue_dir, "stop"), "w").close()
        proc.wait(timeout=120)
    results = []
    for name in sorted(os.listdir(outbox)):
        with open(os.path.join(outbox, name)) as f:
            results.append(json.load(f))
    return results, elapsed


def run_socket(args, stream):
    """Drive the full fleet stack: socket front-end + admission +
    ``--replicas`` worker processes on one shared queue dir."""
    import socket as socketlib

    from qba_tpu.serve.fleet import (
        AdmissionController,
        FleetFrontend,
        ReplicaPool,
        fleet_summary,
        write_fleet_summary,
    )

    if args.chaos_kill and args.replicas < 2:
        raise SystemExit("--chaos-kill needs --replicas >= 2 (a survivor "
                         "must reclaim the victim's claims)")
    queue_dir = args.queue_dir or tempfile.mkdtemp(prefix="qba_fleet_")
    admission = AdmissionController(
        chunk_trials=args.chunk_trials, replicas=args.replicas
    )
    pool = ReplicaPool(
        queue_dir,
        replicas=args.replicas,
        chunk_trials=args.chunk_trials,
        cache_dir=args.cache_dir,
        telemetry_dir=args.telemetry,
        reclaim_timeout_s=args.reclaim_timeout_s,
        poll_s=0.02,
    )
    frontend = FleetFrontend(queue_dir, admission, max_requests=len(stream))
    pool.start()
    t0 = time.perf_counter()
    results = []
    try:
        port = frontend.start_in_thread()
        sock = socketlib.create_connection(
            ("127.0.0.1", port), timeout=args.timeout_s
        )
        wire = sock.makefile("rw")
        for req in stream:
            wire.write(json.dumps(req.to_json()) + "\n")
        wire.flush()
        sock.shutdown(socketlib.SHUT_WR)
        if args.chaos_kill:
            # Wait until the fleet is mid-stream, then SIGKILL one
            # replica; its unclaimed + in-flight work must be reclaimed
            # by the survivors (zero lost requests, asserted in main).
            # Counted via the front-end (not the outbox listing: it
            # moves forwarded results to consumed/ as they land).
            deadline = time.time() + args.timeout_s
            while time.time() < deadline:
                if frontend.results_forwarded >= max(1, len(stream) // 4):
                    break
                time.sleep(0.05)
            victim = pool.alive()[-1]
            pid = pool.kill(victim)
            print(f"chaos: SIGKILL replica {victim} (pid {pid}); "
                  f"survivors {pool.alive()} reclaim its claims")
        for line in wire:
            if line.strip():
                results.append(json.loads(line))
        elapsed = time.perf_counter() - t0
    finally:
        frontend.stop_in_thread()
        codes = pool.stop()
    summary = fleet_summary(
        queue_dir,
        admission_summary=admission.summary(),
        frontend_status=frontend.status(),
        elapsed_s=elapsed,
        telemetry_dir=args.telemetry,
    )
    summary["replica_exit_codes"] = codes
    path = write_fleet_summary(queue_dir, summary)
    print(f"fleet summary:   {path}")
    return results, elapsed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=21)
    ap.add_argument("--trials", type=int, default=6, help="trials per request (base)")
    ap.add_argument("--chunk-trials", type=int, default=8)
    ap.add_argument("--in-process", action="store_true",
                    help="drive QBAServer directly instead of a subprocess")
    ap.add_argument("--transport", choices=("file-queue", "socket"),
                    default="file-queue",
                    help="file-queue = one subprocess server; socket = the "
                    "fleet stack (front-end + admission + --replicas "
                    "workers)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="worker processes for --transport socket")
    ap.add_argument("--chaos-kill", action="store_true",
                    help="socket transport only: SIGKILL one replica "
                    "mid-stream and assert zero lost requests")
    ap.add_argument("--reclaim-timeout-s", type=float, default=30.0,
                    help="fleet crash-recovery reclaim timeout; must "
                    "exceed the worst-case claim-to-result time (cold "
                    "compiles!) or live claims get double-served")
    ap.add_argument("--report-json", default=None,
                    help="write {rpm, p50_s, p99_s, results, replicas} "
                    "to this file (CI compares 1- vs 2-replica rates)")
    ap.add_argument("--queue-dir", default=None)
    ap.add_argument("--telemetry", default=None,
                    help="per-request manifest/trace directory")
    ap.add_argument("--cache-dir", default=None,
                    help="warm-start artifact directory")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--target", default=None,
                    help="precision target applied to every request "
                    "(qba_tpu.stats.parse_target grammar, e.g. "
                    "'decide vs 1/3 @ 95%%'); --trials becomes the "
                    "budget ceiling and requests finish early once "
                    "their stopping rule resolves")
    ap.add_argument("--min-early-stop", type=int, default=0,
                    help="fail unless at least this many targeted "
                    "requests stopped before exhausting their budget "
                    "(the CI stats job asserts the early-stop path "
                    "actually exercised)")
    args = ap.parse_args(argv)

    stream = make_stream(args.requests, args.trials, target=args.target)
    if args.in_process:
        results, elapsed = run_in_process(args, stream)
    elif args.transport == "socket":
        results, elapsed = run_socket(args, stream)
    else:
        results, elapsed = run_subprocess(args, stream)

    errors = [r for r in results if r.get("error")]
    if errors:
        raise SystemExit(f"{len(errors)} requests failed: {errors[:3]}")
    if len(results) != len(stream):
        raise SystemExit(f"got {len(results)} results for {len(stream)} requests")

    # Every result must carry a schema-clean manifest.
    from qba_tpu.obs.manifest import validate_manifest

    for r in results:
        validate_manifest(r["manifest"])

    # Bit-identity spot check: first request of each bucket vs a direct
    # engine run of the identical config.
    from qba_tpu.backends.jax_backend import run_trials, trial_keys

    by_id = {r["request_id"]: r for r in results}
    for req in stream[: len(BUCKETS)]:
        direct = run_trials(req.config(), trial_keys(req.config()))
        import numpy as np

        want = [bool(x) for x in np.asarray(direct.trials.success)]
        got = by_id[req.request_id]["success"]
        # Targeted requests may stop early; the served trials must then
        # be a bit-identical *prefix* of the direct fixed-budget run
        # (chunk keys are a pure function of seed + chunk index).
        if got != want[: len(got)] or (args.target is None and len(got) != len(want)):
            raise SystemExit(f"bit-identity violation on {req.request_id}")

    # p50/p99 from the returned span data: latency_s IS each request's
    # span duration, so feed them back through the span summarizer.
    from qba_tpu.obs.telemetry import span_latency_summary

    spans = [
        types.SimpleNamespace(name="request", dur=r["latency_s"])
        for r in results
    ]
    lat = span_latency_summary(spans, "request")
    rpm = len(results) / elapsed * 60.0
    print(f"requests:        {len(results)} across {len(BUCKETS)} buckets")
    print(f"wall time:       {elapsed:.2f} s")
    print(f"sustained rate:  {rpm:.1f} requests/min")
    print(f"latency p50:     {lat['p50_s'] * 1e3:.1f} ms")
    print(f"latency p99:     {lat['p99_s'] * 1e3:.1f} ms")
    print(f"latency mean:    {lat['mean_s'] * 1e3:.1f} ms  "
          f"(min {lat['min_s'] * 1e3:.1f}, max {lat['max_s'] * 1e3:.1f})")

    if args.transport == "socket":
        # Per-replica attribution: every result names the replica that
        # served it, and queue-wait vs device-time come from its spans.
        per = {}
        for r in results:
            per.setdefault(r.get("replica_id"), []).append(r)
        for rid in sorted(per, key=str):
            rs = per[rid]
            waits = [r["queue_wait_s"] for r in rs
                     if r.get("queue_wait_s") is not None]
            mean_wait = sum(waits) / len(waits) * 1e3 if waits else 0.0
            mean_dev = sum(r["latency_s"] for r in rs) / len(rs) * 1e3
            print(f"replica {rid}:      {len(rs)} requests, "
                  f"mean queue-wait {mean_wait:.1f} ms, "
                  f"mean device-time {mean_dev:.1f} ms")
        admitted = [r for r in results
                    if (r.get("admission") or {}).get("action")]
        if admitted:
            print(f"admission:       {len(admitted)}/{len(results)} "
                  "results carry a typed admission decision")

    if args.target:
        # Time-to-decision: for a targeted request the request span
        # closes when its stopping rule resolves (or the budget runs
        # out), so the same span durations ARE the decision latencies —
        # summarize the decided subset separately from the full stream.
        decided = [
            r for r in results
            if r.get("stop") and r["stop"]["reason"] != "budget_exhausted"
        ]
        # "Early" = decided with trials to spare in the budget.
        early = [
            r for r in decided
            if r["n_trials"]
            < next(q.trials for q in stream if q.request_id == r["request_id"])
        ]
        if decided:
            dspans = [
                types.SimpleNamespace(name="decision", dur=r["latency_s"])
                for r in decided
            ]
            dlat = span_latency_summary(dspans, "decision")
            saved = sum(
                next(q.trials for q in stream
                     if q.request_id == r["request_id"]) - r["n_trials"]
                for r in decided
            )
            print(f"target:          {args.target!r}")
            print(f"decided:         {len(decided)}/{len(results)} "
                  f"({len(early)} early, {saved} budget trials saved)")
            print(f"time-to-decision p50: {dlat['p50_s'] * 1e3:.1f} ms  "
                  f"p99: {dlat['p99_s'] * 1e3:.1f} ms")
        else:
            print(f"target:          {args.target!r} (no request decided "
                  "within budget)")
        if len(early) < args.min_early_stop:
            raise SystemExit(
                f"only {len(early)} requests early-stopped "
                f"(--min-early-stop {args.min_early_stop}): the "
                "precision-target path was not exercised"
            )

    print("manifests:       all valid; bit-identity spot check passed")
    if args.report_json:
        with open(args.report_json, "w") as f:
            json.dump(
                {
                    "rpm": rpm,
                    "p50_s": lat["p50_s"],
                    "p99_s": lat["p99_s"],
                    "results": len(results),
                    "transport": args.transport,
                    "replicas": (
                        args.replicas if args.transport == "socket" else 1
                    ),
                    "chaos_kill": bool(args.chaos_kill),
                    "served_by": sorted(
                        {str(r.get("replica_id")) for r in results}
                    ),
                },
                f,
                indent=1,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
