"""Reproducing the reference's *actual* quirks, not just its intent.

Two behaviors of ``tfg.py`` are implementation accidents rather than
protocol design, and both are available as opt-in modes (next to the
idealized defaults):

* ``attack_scope="broadcast"`` — the 4-action attack mutates shared
  packet objects (``tfg.py:271-284``): a ``P.clear()`` / ``L.clear()``
  chosen for one recipient leaks into every later recipient of the same
  broadcast, and a forged order carries forward.  (Default
  ``"delivery"`` samples each recipient independently.)
* ``racy_mode="defer"`` — the barrier race (``tfg.py:335-348``)
  delivers a late packet one round later, where the
  ``len(L) == round+1`` check rejects it.  (Default ``"loss"`` models
  the same outcome as silent loss.)

The full per-packet event trail (every ``mpi_print`` site of the
reference) shows both mechanisms at work.

Usage: python examples/faithful_quirks.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

from qba_tpu import QBAConfig
from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.obs import EventLog, Level

cfg = QBAConfig(
    n_parties=5,
    size_l=16,
    n_dishonest=2,
    attack_scope="broadcast",
    delivery="racy",
    p_late=0.4,
    racy_mode="defer",
)

log = EventLog(min_level=Level.DEBUG)
result = run_trial_local(cfg, jax.random.key(7), log=log)

leaks = [
    e for e in log.events
    if e.message == "attack" and "+" in e.fields.get("action", "")
]
defers = [e for e in log.events if e.message == "late defer"]
deferred_rejects = [
    e for e in log.events
    if e.message == "receive" and e.fields.get("deferred")
]

print(f"decisions: {result['decisions']}  success: {result['success']}")
print(f"{len(log.events)} protocol events in the trail, including:")
print(f"  {len(leaks)} leaked multi-edit attacks (broadcast scope), e.g.")
for e in leaks[:3]:
    print(f"    {e.render()}")
print(f"  {len(defers)} deferred late packets (defer mode); all "
      f"{len(deferred_rejects)} re-deliveries rejected:")
for e in deferred_rejects[:3]:
    print(f"    {e.render()}")
