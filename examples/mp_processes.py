"""The reference's runtime shape for real: one OS process per party.

The reference launches `mpiexec -n <nParties+1> python tfg.py ...` — one
OS process per protocol rank exchanging tagged MPI messages
(``tfg.py:310-314``).  The ``mp`` backend reproduces exactly that shape:
this (coordinator) process plays the QSD/rank-0 role, every party runs
as its own spawned OS process, the parties self-assemble a full
point-to-point Unix-socket mesh, and every packet crosses a real process
boundary through the C++ PvL wire codec.

The same trial key produces bit-identical decisions on every backend —
here we run one adversarial trial on ``mp`` and on the in-process
``local`` backend and diff them, then print the per-packet protocol
trail the party processes reported back.

Round 4 adds batch mode: one persistent mesh serves a whole batch of
trials (:func:`qba_tpu.backends.mp_backend.run_trials_mp` — the
coordinator streams each trial's presampled randomness over the work
pipes), demonstrated below after the single-trial differential.

Usage: python examples/mp_processes.py   (CPU-friendly; needs g++ once
for the native codec build).  The ``__main__`` guard is kept for the
spawn/forkserver fallback start methods (the default is ``fork``).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main():
    import jax

    from qba_tpu import QBAConfig
    from qba_tpu.backends.local_backend import run_trial_local
    from qba_tpu.backends.mp_backend import run_trial_mp
    from qba_tpu.obs import EventLog, Level

    cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, seed=0)
    key = jax.random.key(1)

    log = EventLog(min_level=Level.DEBUG)
    mp_res = run_trial_mp(cfg, key, log=log)
    local_res = run_trial_local(cfg, key)

    print(f"config: {cfg.n_parties} parties (= {cfg.n_parties} OS "
          f"processes + this coordinator), {cfg.n_dishonest} dishonest")
    print(f"mp    decisions: {mp_res['decisions']}")
    print(f"local decisions: {local_res['decisions']}")
    assert mp_res["decisions"] == local_res["decisions"]
    assert mp_res["vi"] == local_res["vi"]
    print("bit-identical across the process boundary: OK")

    print("\nper-packet trail (reassembled from the party processes):")
    for ev in log.events:
        if ev.phase in ("round", "step2", "step3a", "decision"):
            print(f"  {ev.render()}")

    # Batch mode: the same mesh serves many trials (one spawn total).
    from qba_tpu.backends.jax_backend import trial_keys
    from qba_tpu.backends.mp_backend import run_trials_mp

    cfg_b = QBAConfig(
        n_parties=5, size_l=16, n_dishonest=2, trials=4, seed=0
    )
    keys = list(trial_keys(cfg_b))
    batch = run_trials_mp(cfg_b, keys)
    for k, got in zip(keys, batch):
        ref = run_trial_local(cfg_b, k)
        assert got["decisions"] == ref["decisions"]
        assert got["vi"] == ref["vi"]
    n_ok = sum(r["success"] for r in batch)
    print(f"\nbatch mode: {len(batch)} trials over ONE persistent mesh, "
          f"{n_ok} successes, every trial bit-identical to local: OK")


if __name__ == "__main__":
    main()
