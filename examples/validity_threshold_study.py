"""Security-parameter study: validity & agreement over (nDishonest, sizeL).

The reference demonstrated its threshold behavior anecdotally (one
``log_d_11.txt`` run); this maps it.  For each (nDishonest, sizeL) grid
point at fixed ``n_parties``, runs >= ``--trials`` Monte-Carlo trials
and records, with Wilson 95% intervals (``qba_tpu.obs.stats``):

* overall success (the oracle: all honest parties agree),
* VALIDITY — success conditional on an honest commander (honest
  lieutenants decide the commander's order; the protocol's security
  claim, and the property whose 11p/d=5 counterexample
  ``tests/test_reference_scale.py`` recorded in round 4),
* agreement conditional on a dishonest commander.

Writes ``validity_study.json`` + a matplotlib figure to ``--out``.

Usage:
  python examples/validity_threshold_study.py               # full grid (TPU, ~20 min)
  python examples/validity_threshold_study.py --quick       # CI-sized smoke
  python examples/validity_threshold_study.py \
      --atlas-store runs/atlas --seed 0 --target 'decide vs 1/3'
                      # serve grid points from certified atlas cells
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_point(cfg, total_trials: int, chunk: int, rule=None):
    """Accumulate success/honesty/decisions across chunked batches.

    With ``rule`` (a stopping rule from ``Target.make_rule()``) the
    point runs in precision-targeted mode: chunks keep their fixed-
    budget keys (so the targeted run is a bit-identical prefix of the
    full one) but the loop exits as soon as the rule resolves on the
    overall success rate.  Returns the trial arrays plus the
    StopDecision (None in fixed-budget mode)."""
    import jax

    from qba_tpu.backends.jax_backend import fence, run_trials

    succ, hon, dec, vc = [], [], [], []
    n_chunks = -(-total_trials // chunk)
    cfg_c = dataclasses.replace(cfg, trials=chunk)
    stop = None
    for i in range(n_chunks):
        keys = jax.random.split(
            jax.random.key(cfg.seed * 1_000_003 + i), chunk
        )
        res = run_trials(cfg_c, keys)
        fence(res)
        s = np.asarray(res.trials.success)
        succ.append(s)
        hon.append(np.asarray(res.trials.honest))
        dec.append(np.asarray(res.trials.decisions))
        vc.append(np.asarray(res.trials.v_comm))
        if rule is not None:
            rule.observe(int(s.sum()), int(s.size))
            stop = rule.decision()
            if stop is not None:
                break
    if rule is not None and stop is None:
        stop = rule.exhausted()
    return (
        np.concatenate(succ),
        np.concatenate(hon),
        np.concatenate(dec),
        np.concatenate(vc),
        stop,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-parties", type=int, default=11)
    ap.add_argument("--dishonest", default="1,2,3,4,5")
    ap.add_argument("--size-l", default="4,16,64,256,1000")
    ap.add_argument("--trials", type=int, default=10_000)
    ap.add_argument(
        "--strategy", default="reference",
        help="adversary-zoo strategy the grid runs under "
        "(reference/collude/adaptive/split; docs/ARCHITECTURE.md)",
    )
    ap.add_argument("--p-depolarize", type=float, default=0.0)
    ap.add_argument("--p-measure-flip", type=float, default=0.0)
    ap.add_argument("--out", default="docs/assets")
    ap.add_argument("--quick", action="store_true",
                    help="tiny grid for CI/smoke (overrides the above)")
    ap.add_argument(
        "--target", default=None,
        help="precision-targeted mode (qba_tpu.stats grammar, e.g. "
        "'ci_width<=0.05 @ 95%%' or 'decide vs 1/3'): each grid point "
        "stops as soon as its stopping rule resolves on the overall "
        "success rate, with --trials as the budget ceiling; points "
        "then carry an anytime-valid CI and a stop record "
        "(docs/STATS.md)",
    )
    ap.add_argument(
        "--atlas-store", default=None, metavar="DIR",
        help="serve grid points from certified atlas cells "
        "(qba-tpu atlas; docs/ATLAS.md) instead of re-running them: a "
        "point whose exact config fingerprint has a certified record "
        "satisfying --target is a cache hit (overall rate + CI only — "
        "the validity/profile breakdowns need trial arrays the store "
        "does not keep); hit/miss counts are printed and recorded",
    )
    ap.add_argument(
        "--seed", type=int, default=None,
        help="fixed config seed for every grid point (default: the "
        "per-point 17*d+L recipe); a campaign stamps its spec seed on "
        "every cell, so pass that seed for --atlas-store hits",
    )
    args = ap.parse_args()

    from qba_tpu.compile_cache import enable_compile_cache
    from qba_tpu.config import QBAConfig
    from qba_tpu.obs.stats import decision_profile, study_breakdown

    enable_compile_cache()

    if args.quick:
        n_p, ds, ls, trials = 5, [1, 2], [4, 16], 256
    else:
        n_p = args.n_parties
        ds = [int(x) for x in args.dishonest.split(",")]
        ls = [int(x) for x in args.size_l.split(",")]
        trials = args.trials

    store = None
    if args.atlas_store:
        from qba_tpu.atlas.store import AtlasStore

        store = AtlasStore(args.atlas_store)
    hits = misses = 0

    points = []
    for d in ds:
        for L in ls:
            cfg = QBAConfig(
                n_parties=n_p, size_l=L, n_dishonest=d,
                trials=trials,
                seed=args.seed if args.seed is not None else 17 * d + L,
                strategy=args.strategy,
                p_depolarize=args.p_depolarize,
                p_measure_flip=args.p_measure_flip,
            )
            if store is not None:
                fp = dataclasses.asdict(cfg)
                fp.pop("trials", None)
                rec = store.lookup(fp, args.target)
                if rec is not None:
                    hits += 1
                    ci = rec.get("ci") or {}
                    points.append({
                        "overall": dict(ci),
                        "validity": {"rate": None, "lo": None,
                                     "hi": None, "n": 0},
                        "n_parties": n_p, "n_dishonest": d, "size_l": L,
                        "strategy": args.strategy,
                        "p_depolarize": args.p_depolarize,
                        "p_measure_flip": args.p_measure_flip,
                        "trials": rec.get("n_trials"),
                        "stop": rec.get("stop"),
                        "from_atlas": True,
                        "cell_key": rec.get("cell_key"),
                    })
                    print(
                        f"d={d} L={L:4d}: overall {ci.get('rate'):.4f} "
                        f"[{ci.get('lo'):.4f},{ci.get('hi'):.4f}]  "
                        f"(atlas hit {rec.get('cell_key')}, "
                        f"{rec.get('n_trials')} trials)",
                        flush=True,
                    )
                    continue
                misses += 1
            # Chunk by pool footprint: sizeL=1000 at 10k trials would
            # blow the single-batch HBM ceiling (KI-2).
            chunk = min(trials, 2000 if L <= 256 else 500)
            rule = None
            if args.target:
                from qba_tpu.stats import parse_target

                rule = parse_target(args.target).make_rule()
            t0 = time.time()
            succ, hon, dec, vc, stop = run_point(cfg, trials, chunk, rule)
            b = study_breakdown(succ, hon[:, 0])
            b["profile"] = decision_profile(dec, hon, vc, cfg.w)
            b.update(n_parties=n_p, n_dishonest=d, size_l=L,
                     strategy=args.strategy,
                     p_depolarize=args.p_depolarize,
                     p_measure_flip=args.p_measure_flip,
                     trials=int(succ.size), seconds=round(time.time() - t0, 1))
            if stop is not None:
                # Error bars safe to read at the stopping time: the
                # rule's own anytime-valid estimate, not the fixed-n
                # Wilson bands the fixed-budget columns use.
                b["stop"] = stop.to_json()
                b["overall_anytime"] = rule.estimate().to_json()
            points.append(b)
            va, pr = b["validity"], b["profile"]

            def r(x, nd=4):  # a zero-honest-commander point has rate None
                return "  n/a " if x["rate"] is None else f"{x['rate']:.{nd}f}"

            tail = f"({va['n']} hc-trials, {b['seconds']}s)"
            if stop is not None:
                tail = (
                    f"(stop={stop.reason} @ {stop.n_trials}/{trials} "
                    f"trials, {b['seconds']}s)"
                )
            print(
                f"d={d} L={L:4d}: overall {r(b['overall'])}  "
                f"validity {r(va)} [{va['lo']:.4f},{va['hi']:.4f}]  "
                f"abort {r(pr['abort_all'], 3)} "
                f"mixed {r(pr['mixed_valid_abort'], 3)} "
                f"corrupt {r(pr['corrupted'], 3)} {tail}",
                flush=True,
            )

    os.makedirs(args.out, exist_ok=True)
    json_path = os.path.join(args.out, "validity_study.json")
    payload = {"n_parties": n_p, "points": points}
    if args.target:
        payload["target"] = args.target
    if store is not None:
        payload["atlas"] = {
            "store": args.atlas_store, "hits": hits, "misses": misses,
        }
        print(f"atlas store {args.atlas_store}: "
              f"{hits} hit(s), {misses} miss(es)")
    with open(json_path, "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote", json_path)

    try:
        _plot(points, ds, ls, n_p, os.path.join(args.out, "validity_study.png"))
    except Exception as e:  # matplotlib optional
        print(f"plot skipped: {e!r}")


def _plot(points, ds, ls, n_p, path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    by = {(p["n_dishonest"], p["size_l"]): p for p in points}
    fig, (ax1, ax2, ax3) = plt.subplots(1, 3, figsize=(15, 4), dpi=150)
    cmap = plt.get_cmap("viridis")
    for i, d in enumerate(ds):
        color = cmap(i / max(len(ds) - 1, 1))
        xs = [
            L for L in ls
            if (d, L) in by
            and by[(d, L)]["validity"]["rate"] is not None
        ]
        va = [by[(d, L)]["validity"] for L in xs]
        ax1.fill_between(xs, [v["lo"] for v in va], [v["hi"] for v in va],
                         color=color, alpha=0.15, lw=0)
        ax1.plot(xs, [v["rate"] for v in va], color=color, marker="o",
                 ms=4, lw=1.8, label=f"d={d}")
        pr = [by[(d, L)]["profile"] for L in xs]
        corrupt = [p["corrupted"]["rate"] for p in pr]
        detect = [
            p["abort_all"]["rate"] + p["mixed_valid_abort"]["rate"]
            for p in pr
        ]
        ax2.plot(xs, corrupt, color=color, marker="v", ms=4, lw=1.8,
                 label=f"corrupted d={d}")
        ax2.plot(xs, detect, color=color, marker="^", ms=4, lw=1.2,
                 ls="--", label=f"detected d={d}")
        ag = [by[(d, L)]["agreement_dishonest_c"] for L in xs]
        ax3.plot(xs, [a["rate"] for a in ag], color=color, marker="s",
                 ms=4, lw=1.8, label=f"d={d}")
        ax3.fill_between(xs, [a["lo"] for a in ag], [a["hi"] for a in ag],
                         color=color, alpha=0.15, lw=0)
    for ax, title in (
        (ax1, "validity: all honest lieutenants decide the order"
              " | honest commander"),
        (ax2, "failure split | honest commander:\n"
              "corrupted (solid) vs detected/abort (dashed)"),
        (ax3, "agreement | dishonest commander"),
    ):
        ax.set_xscale("log", base=2)
        ax.set_xlabel("sizeL (security parameter)")
        ax.set_ylim(-0.02, 1.02)
        ax.grid(alpha=0.25)
        ax.set_title(title, fontsize=9)
        ax.legend(fontsize=7)
    fig.suptitle(f"QBA threshold study, n_parties={n_p} "
                 f"(Wilson 95% bands)", fontsize=11)
    fig.tight_layout()
    fig.savefig(path)
    print("wrote", path)


if __name__ == "__main__":
    main()
