"""Reference-style circuit construction through the compat API.

The same call shapes as the reference's qsimov usage (tfg.py:15-80):
QGate + add_operation, QCircuit + MEASURE, Drewom().execute.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


from qba_tpu.qsim import Drewom, QCircuit, QGate

n_parties, n_qubits = 3, 2
size = (n_parties + 1) * n_qubits

# The not-Q-correlated resource circuit (tfg.py:15-22): H on groups
# 1..n, CNOT copying group 1 onto group 0.
gate = QGate(size, 0, "notQCorrelated")
for q in range(n_qubits, size):
    gate.add_operation("H", targets=q)
for b in range(n_qubits):
    gate.add_operation("X", targets=b, controls=n_qubits + b)

circuit = QCircuit(size, size, "NQCorrCircuit")
circuit.add_operation(gate)
for i in range(size):
    circuit.add_operation("MEASURE", targets=i, outputs=i)

for shot, bits in enumerate(Drewom(seed=0).execute(circuit, shots=4)):
    groups = [bits[g * n_qubits:(g + 1) * n_qubits] for g in range(n_parties + 1)]
    print(f"shot {shot}: groups={groups}  (group 0 == group 1: "
          f"{groups[0] == groups[1]})")
