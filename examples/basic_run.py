"""Minimal end-to-end run — the `mpiexec -n 12 python tfg.py 64 3` analog.

Usage: python examples/basic_run.py   (CPU or TPU; no flags needed)
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


from qba_tpu import QBAConfig, run_trials

cfg = QBAConfig(n_parties=11, size_l=64, n_dishonest=3, trials=100, seed=0)
res = run_trials(cfg)

print(f"config: {cfg.n_parties} parties, sizeL={cfg.size_l}, "
      f"{cfg.n_dishonest} dishonest, w={cfg.w}")
print(f"success rate over {cfg.trials} trials: {float(res.success_rate):.3f}")

# Per-trial detail, reference-style (tfg.py:360-363): decisions of parties
# 1..n (commander first), who was dishonest, and the verdict.
import numpy as np

t = 0
decisions = np.asarray(res.trials.decisions[t])
honest = np.asarray(res.trials.honest[t])
print(f"\ntrial {t}:")
print(f"Decisions:  {decisions.tolist()}")
print(f"Dishonests: {[i + 1 for i, h in enumerate(honest) if not h]}")
print(f"Success:    {bool(res.trials.success[t])}")
