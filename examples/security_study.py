"""Success probability vs the security parameter sizeL.

The protocol's agreement guarantee sharpens as the particle lists grow;
this sweeps sizeL and (optionally) plots the curve.

Usage: python examples/security_study.py [out.png]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


from qba_tpu import QBAConfig, run_trials

values = [1, 2, 4, 8, 16, 32, 64]
rates = []
for L in values:
    cfg = QBAConfig(n_parties=5, size_l=L, n_dishonest=2, trials=256, seed=7)
    rate = float(run_trials(cfg).success_rate)
    rates.append(rate)
    print(f"sizeL={L:3d}: success_rate={rate:.4f}")

if len(sys.argv) > 1:
    from qba_tpu.obs.plots import plot_param_study

    print("plot:", plot_param_study(values, rates, 256, "size_l",
                                    sys.argv[1], log_x=True))
