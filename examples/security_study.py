"""Success probability over the (strategy × noise × sizeL) surface.

The protocol's agreement guarantee sharpens as the particle lists grow;
this maps that curve against the adversary zoo (strategy-indexed
Byzantine fault injection, docs/ARCHITECTURE.md) and imperfect quantum
resources (depolarizing + readout flip, qba_tpu/qsim/noise.py) in ONE
sharded Monte-Carlo run: every cell goes through
``qba_tpu.sweep.run_surface`` — dp-sharded over all visible devices,
checkpoint-resumable, with per-cell kernel-plan manifest attribution.

Usage:
  python examples/security_study.py                 # full surface
  python examples/security_study.py --quick         # CI-sized smoke
  python examples/security_study.py --json out.json # surface + manifests
  python examples/security_study.py --plot out.png  # per-strategy curves
  python examples/security_study.py --atlas-store runs/atlas \
      --seed 0 --target 'decide vs 1/3'  # serve cells from the atlas
"""

import argparse
import dataclasses
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from qba_tpu import QBAConfig  # noqa: E402
from qba_tpu.adversary import STRATEGIES  # noqa: E402
from qba_tpu.sweep import run_surface  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-parties", type=int, default=5)
    ap.add_argument("--dishonest", type=int, default=2)
    ap.add_argument("--trials", type=int, default=256)
    ap.add_argument("--size-l", default="1,2,4,8,16,32,64")
    ap.add_argument(
        "--strategies", default=",".join(STRATEGIES),
        help="comma list from the zoo (default: all)",
    )
    ap.add_argument(
        "--noise", default="0:0,0.02:0.01",
        help="comma list of p_depolarize:p_measure_flip pairs",
    )
    ap.add_argument("--n-chunks", type=int, default=1,
                    help="chunks per cell (per-cell budget ceiling in "
                    "targeted mode)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--target", default=None,
                    help="precision target (qba_tpu.stats grammar): the "
                    "adaptive allocator spends chunks on the least-"
                    "resolved cells first and each cell stops once its "
                    "rule fires (docs/STATS.md)")
    ap.add_argument("--budget-chunks", type=int, default=None,
                    help="total chunk budget across all cells in "
                    "targeted mode (default: n_chunks x n_cells); "
                    "ignored with --atlas-store, where miss cells run "
                    "one at a time with the per-cell n_chunks ceiling")
    ap.add_argument("--atlas-store", default=None, metavar="DIR",
                    help="serve surface cells from certified atlas "
                    "records (qba-tpu atlas; docs/ATLAS.md): a cell "
                    "whose exact config fingerprint has a certified "
                    "record satisfying --target is a cache hit and is "
                    "not re-run; misses run and are published back "
                    "into the store; hit/miss counts are printed")
    ap.add_argument("--seed", type=int, default=7,
                    help="config seed for every cell (a campaign "
                    "stamps its spec seed on every cell, so match it "
                    "for --atlas-store hits)")
    ap.add_argument("--json", default=None, help="write the surface (with "
                    "per-cell manifests) as JSON")
    ap.add_argument("--plot", default=None, help="PNG of per-strategy "
                    "curves at zero noise (requires matplotlib)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny surface for CI/smoke")
    args = ap.parse_args()

    if args.quick:
        strategies = ["reference", "split"]
        noise_points = [(0.0, 0.0), (0.05, 0.02)]
        size_ls = [4, 16]
        trials = 64
    else:
        strategies = [s for s in args.strategies.split(",") if s]
        noise_points = [
            tuple(float(x) for x in pair.split(":"))
            for pair in args.noise.split(",")
        ]
        size_ls = [int(x) for x in args.size_l.split(",")]
        trials = args.trials

    cfg = QBAConfig(
        n_parties=args.n_parties, size_l=size_ls[0],
        n_dishonest=args.dishonest, trials=trials, seed=args.seed,
    )

    grid = [
        (s, (p, q), L)
        for s in strategies
        for (p, q) in noise_points
        for L in size_ls
    ]
    atlas_hits = []
    if args.atlas_store:
        from qba_tpu.atlas.store import AtlasStore

        store = AtlasStore(args.atlas_store)
        pending = []
        for s, (p, q), L in grid:
            cfg_cell = dataclasses.replace(
                cfg, strategy=s, p_depolarize=p, p_measure_flip=q,
                size_l=L,
            )
            fp = dataclasses.asdict(cfg_cell)
            fp.pop("trials", None)
            rec = store.lookup(fp, args.target)
            if rec is not None:
                atlas_hits.append(rec)
            else:
                pending.append((s, (p, q), L))
        print(f"atlas store {args.atlas_store}: {len(atlas_hits)} "
              f"hit(s), {len(pending)} miss(es)")
        # Misses run one cell at a time (each publishing its record
        # back into the store) so hits are never re-simulated; the
        # cross-cell adaptive budget only applies to the no-store path.
        cells = []
        for s, pq, L in pending:
            cells += run_surface(
                cfg,
                strategies=[s],
                noise_points=[pq],
                size_ls=[L],
                n_chunks=args.n_chunks,
                chunk_trials=trials,
                checkpoint_dir=args.checkpoint_dir,
                target=args.target,
                store_dir=args.atlas_store,
            )
    else:
        cells = run_surface(
            cfg,
            strategies=strategies,
            noise_points=noise_points,
            size_ls=size_ls,
            n_chunks=args.n_chunks,
            chunk_trials=trials,
            checkpoint_dir=args.checkpoint_dir,
            target=args.target,
            budget_chunks=args.budget_chunks,
        )

    for rec in atlas_hits:
        co = rec.get("coords") or {}
        ci = rec.get("ci") or {}
        print(
            f"strategy={co.get('strategy', '?'):9s} "
            f"p={co.get('p_depolarize', 0.0):.3f} "
            f"q={co.get('p_measure_flip', 0.0):.3f} "
            f"sizeL={co.get('size_l', 0):4d}: "
            f"success_rate={ci.get('rate', float('nan')):.4f} "
            f"(atlas hit {rec.get('cell_key')}, "
            f"{rec.get('n_trials')} trials)"
        )
    for c in cells:
        plan = (c.manifest or {}).get("plan", {})
        stop = ""
        if c.result.stop is not None:
            stop = f" stop={c.result.stop.reason}"
        print(
            f"strategy={c.strategy:9s} p={c.p_depolarize:.3f} "
            f"q={c.p_measure_flip:.3f} sizeL={c.size_l:4d}: "
            f"success_rate={c.result.success_rate:.4f} "
            f"({c.result.n_trials} trials, "
            f"engine={plan.get('engine', '?')}){stop}"
        )

    if args.json:
        # Surface-with-error-bars: each cell's rate is the certified
        # estimate object (rate/lo/hi, KI-8), never a bare float.
        payload = [
            {
                "strategy": c.strategy,
                "p_depolarize": c.p_depolarize,
                "p_measure_flip": c.p_measure_flip,
                "size_l": c.size_l,
                "trials": c.result.n_trials,
                "success_rate": c.result.stats_summary()["success_rate"],
                "stop": c.result.stop.to_json() if c.result.stop else None,
                "manifest": c.manifest,
            }
            for c in cells
        ]
        payload += [
            {
                "strategy": (rec.get("coords") or {}).get("strategy"),
                "p_depolarize": (rec.get("coords") or {}).get(
                    "p_depolarize"),
                "p_measure_flip": (rec.get("coords") or {}).get(
                    "p_measure_flip"),
                "size_l": (rec.get("coords") or {}).get("size_l"),
                "trials": rec.get("n_trials"),
                "success_rate": rec.get("ci"),
                "stop": rec.get("stop"),
                "from_atlas": True,
                "cell_key": rec.get("cell_key"),
            }
            for rec in atlas_hits
        ]
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        print("wrote", args.json)

    if args.plot:
        from qba_tpu.obs.plots import plot_param_study

        for strat in strategies:
            pts = [
                c for c in cells
                if c.strategy == strat
                and c.p_depolarize == 0.0 and c.p_measure_flip == 0.0
            ]
            if len(pts) > 1:
                path = args.plot.replace(".png", f"_{strat}.png")
                print("plot:", plot_param_study(
                    [c.size_l for c in pts],
                    [c.result.success_rate for c in pts],
                    trials, "size_l", path, log_x=True,
                ))


if __name__ == "__main__":
    main()
