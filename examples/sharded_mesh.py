"""Monte-Carlo over a device mesh: trials x positions (dp x sp) and the
party-sharded spmd engine (dp x tp, one all_gather per round over ICI).

Runs on real multi-chip TPU, or on a virtual 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sharded_mesh.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


import jax

from qba_tpu import QBAConfig
from qba_tpu.parallel import (
    default_mesh_shape,
    make_mesh,
    run_trials_sharded,
    run_trials_spmd,
)

n = len(jax.devices())
print(f"{n} devices: {jax.devices()}")

# Trials over dp, list positions over sp; default_mesh_shape factorizes
# any device count, and trials/size_l are sized to divide the axes.
shape = default_mesh_shape(n)
mesh = make_mesh(shape)
dp, sp = shape["dp"], shape.get("sp", 1)
cfg = QBAConfig(n_parties=5, size_l=32 * sp, n_dishonest=1,
                trials=16 * dp, seed=3)
res = run_trials_sharded(cfg, mesh)
print(f"{shape}: success_rate={float(res.success_rate):.3f}")

# Lieutenants over tp: the per-round mailbox exchange is one all_gather.
shape = default_mesh_shape(n, want_tp=True)
if shape.get("tp", 1) > 1:
    mesh = make_mesh(shape)
    dp, tp = shape["dp"], shape["tp"]
    cfg = QBAConfig(n_parties=2 * tp + 1, size_l=32, n_dishonest=1,
                    trials=16 * dp, seed=3)
    res = run_trials_spmd(cfg, mesh)
    print(f"{shape}: success_rate={float(res.success_rate):.3f}")
