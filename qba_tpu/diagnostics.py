"""Dedicated warning categories for engine demotions and compile probes.

Every engine resolver in this repo degrades gracefully: a kernel that
fails its compile probe demotes to the next engine in the preference
order, a transient tunnel error skips caching, a VMEM pre-filter
rejects a shape without probing.  Those events used to surface as bare
``RuntimeWarning`` s, so tests (and the ``qba_tpu.analysis`` lint
driver) could only filter them by message substring.  The categories
below make the filter structural:

* :class:`QBADemotionWarning` — an engine/variant DEMOTION actually
  happened: the caller asked for (or auto-resolution preferred) a
  faster path and got a slower, semantically identical one
  (fused -> tiled, parallel accept -> serial chain, spmd kernel ->
  XLA fallback).
* :class:`QBAProbeWarning` — a compile PROBE failed, was pre-filtered,
  or hit a transient error whose verdict could not be cached.  A probe
  warning often precedes a demotion warning; the probe category tells
  you *why*, the demotion category tells you *what changed*.

Both subclass ``RuntimeWarning`` so existing ``-W`` configurations and
``pytest.warns(RuntimeWarning)`` assertions keep matching.
"""

from __future__ import annotations


class QBAWarning(RuntimeWarning):
    """Base class for all qba_tpu runtime diagnostics."""


class QBADemotionWarning(QBAWarning):
    """An engine, kernel variant, or spmd path was demoted to a slower
    bit-identical fallback (e.g. fused -> two-kernel tiled, parallel
    accept reduction -> serial chain, party-sharded kernel -> XLA)."""


class QBAProbeWarning(QBAWarning):
    """A kernel compile probe failed, was rejected by a VMEM
    pre-filter, or hit a transient (tunnel/infrastructure) error whose
    verdict was deliberately not cached."""
