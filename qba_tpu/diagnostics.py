"""Dedicated warning categories for engine demotions and compile probes.

Every engine resolver in this repo degrades gracefully: a kernel that
fails its compile probe demotes to the next engine in the preference
order, a transient tunnel error skips caching, a VMEM pre-filter
rejects a shape without probing.  Those events used to surface as bare
``RuntimeWarning`` s, so tests (and the ``qba_tpu.analysis`` lint
driver) could only filter them by message substring.  The categories
below make the filter structural:

* :class:`QBADemotionWarning` — an engine/variant DEMOTION actually
  happened: the caller asked for (or auto-resolution preferred) a
  faster path and got a slower, semantically identical one
  (fused -> tiled, parallel accept -> serial chain, spmd kernel ->
  XLA fallback).
* :class:`QBAProbeWarning` — a compile PROBE failed, was pre-filtered,
  or hit a transient error whose verdict could not be cached.  A probe
  warning often precedes a demotion warning; the probe category tells
  you *why*, the demotion category tells you *what changed*.

Both subclass ``RuntimeWarning`` so existing ``-W`` configurations and
``pytest.warns(RuntimeWarning)`` assertions keep matching.

Every warn site routes through :func:`warn_and_record`, which warns
exactly as before (same message, category, and effective stacklevel)
AND hands a structured record to any registered decision hooks — the
run-manifest machinery (:mod:`qba_tpu.obs.manifest`) registers one so a
demotion/probe event is simultaneously a warning for humans and a
manifest entry for machines.  With no hooks registered the helper is
just ``warnings.warn``.
"""

from __future__ import annotations

import contextlib
import warnings
from typing import Any, Callable, Iterator


class QBAWarning(RuntimeWarning):
    """Base class for all qba_tpu runtime diagnostics."""


class QBADemotionWarning(QBAWarning):
    """An engine, kernel variant, or spmd path was demoted to a slower
    bit-identical fallback (e.g. fused -> two-kernel tiled, parallel
    accept reduction -> serial chain, party-sharded kernel -> XLA)."""


class QBAProbeWarning(QBAWarning):
    """A kernel compile probe failed, was rejected by a VMEM
    pre-filter, or hit a transient (tunnel/infrastructure) error whose
    verdict was deliberately not cached."""


class QBACheckpointMismatch(QBAWarning, ValueError):
    """A sweep checkpoint does not match the requested run.

    Dual-natured by design: raised like the historical bare
    ``ValueError`` (existing ``pytest.raises(ValueError, ...)`` pins
    keep matching), but a ``QBAWarning`` family member so
    ``--resume-force`` can *warn* with the same category when it
    re-chunks instead of refusing.  Carries both fingerprints so
    callers/tooling can diff exactly what disagreed.

    ``kind`` is ``"config"`` (never forceable — the checkpointed trials
    were drawn from a different program) or ``"chunk_trials"``
    (forceable — same config, different chunking; re-running re-chunks
    from scratch and overwrites).
    """

    def __init__(
        self,
        message: str,
        *,
        # Optional so ``warnings.warn(msg, QBACheckpointMismatch)`` can
        # instantiate the category from the message alone.
        kind: str = "chunk_trials",
        path: str = "",
        checkpoint_fingerprint: Any = None,
        requested_fingerprint: Any = None,
    ):
        super().__init__(message)
        self.kind = kind
        self.path = path
        self.checkpoint_fingerprint = checkpoint_fingerprint
        self.requested_fingerprint = requested_fingerprint

    @property
    def forceable(self) -> bool:
        return self.kind == "chunk_trials"


# Decision hooks: callables receiving the structured record of every
# warn_and_record call.  A hook must never raise (it runs inside engine
# resolution); exceptions are swallowed so telemetry can never change
# dispatch behavior.
_DECISION_HOOKS: list[Callable[[dict], None]] = []


def add_decision_hook(hook: Callable[[dict], None]) -> Callable[[dict], None]:
    _DECISION_HOOKS.append(hook)
    return hook


def remove_decision_hook(hook: Callable[[dict], None]) -> None:
    try:
        _DECISION_HOOKS.remove(hook)
    except ValueError:
        pass


@contextlib.contextmanager
def record_decisions() -> Iterator[list[dict]]:
    """Collect every dispatch decision warned inside the block.

    Yields the (live) list of records.  NOTE the resolver memos: probe
    and demotion warnings fire on the FIRST resolution of a config
    shape per process (``_RESOLVE_CACHE`` / the probe caches in
    :mod:`qba_tpu.ops.round_kernel_tiled`), so a block entered after
    the shape was already resolved collects nothing — the manifest
    therefore also reads the memoized plan itself
    (:func:`qba_tpu.benchmark.kernel_plan`), which re-reads the cached
    verdicts the run actually used."""
    records: list[dict] = []
    hook = add_decision_hook(records.append)
    try:
        yield records
    finally:
        remove_decision_hook(hook)


def warn_and_record(
    message: str,
    category: type[Warning],
    *,
    site: str,
    stacklevel: int = 2,
    **fields: Any,
) -> None:
    """``warnings.warn`` + structured record, in that order of fidelity:
    the warning text/category/stacklevel are EXACTLY what the call site
    used to emit inline (``pytest.warns(..., match=...)`` suites pin
    them), the record adds the machine-readable context the text loses.

    ``site`` names the emitting resolver (e.g.
    ``"ops.round_kernel.kernel_compiles"``); ``fields`` carry the
    decision specifics (engine_from/engine_to, reason, config shape...).
    ``stacklevel`` is interpreted relative to the *caller* — the extra
    frame this helper adds is compensated internally.
    """
    record = {
        "kind": (
            "demotion"
            if issubclass(category, QBADemotionWarning)
            else "checkpoint"
            if issubclass(category, QBACheckpointMismatch)
            else "probe"
        ),
        "category": category.__name__,
        "site": site,
        "message": message,
        **{k: v for k, v in fields.items()},
    }
    for hook in list(_DECISION_HOOKS):
        try:
            hook(record)
        except Exception:  # telemetry must never alter dispatch
            pass
    warnings.warn(message, category, stacklevel=stacklevel + 1)
