"""End-to-end single-trial protocol engine.

The reference's orchestrator ``QBA`` (``tfg.py:309-363``) branches on MPI
rank; here every phase is an array op over the party axis:

* dishonesty assignment  -> honesty mask          (``tfg.py:101-125``)
* particle distribution  -> qsim generation        (``tfg.py:132-163``)
* step 1b + step 2       -> per-recipient P, v     (``tfg.py:166-184,325-329``)
* step 3a                -> vmapped first receive  (``tfg.py:185-196``)
* step 3b round loop     -> ``lax.scan`` over a dense mailbox
                            (``tfg.py:289-300,337-348``)
* decision + oracle      -> masked min + singleton check
                            (``tfg.py:303-306,351-363``)

Rounds are synchronous by construction (docs/DIVERGENCES.md D1); packet
processing order within a round is (sender, slot) lexicographic (D5).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from qba_tpu.adversary import (
    assign_dishonest,
    commander_orders,
    corrupt_at_delivery,
    late_drop,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core import append_own, consistent, decide_order, success_oracle
from qba_tpu.core.types import SENTINEL, Evidence, Packet, empty_evidence
from qba_tpu.qsim import generate_lists_for
from qba_tpu.rounds.mailbox import Mailbox, empty_mailbox


@dataclasses.dataclass(frozen=True)
class PartitionHints:
    """Optional internal sharding constraints for :func:`run_trial`.

    Hashable (usable as a jit static argument).  ``lists`` is applied to
    the generated party-lists tensor ``[n_parties+1, size_l]`` — e.g.
    ``NamedSharding(mesh, P(None, "sp"))`` shards the position axis (the
    protocol's sequence axis, SURVEY §5) and lets XLA partition every
    positionwise op and insert the reductions ``consistent`` needs.
    """

    lists: jax.sharding.NamedSharding | None = None


@struct.dataclass
class TrialResult:
    """Everything rank 0 prints at the end of a run (``tfg.py:351-363``),
    plus TPU-design diagnostics."""

    success: jnp.ndarray  # bool
    decisions: jnp.ndarray  # int32[n_parties], index 0 = commander (rank 1)
    honest: jnp.ndarray  # bool[n_parties], same indexing
    v_comm: jnp.ndarray  # int32 — the commander's privately chosen order
    vi: jnp.ndarray  # bool[n_lieutenants, w] accepted-sets
    overflow: jnp.ndarray  # bool — a rebroadcast exceeded the slot bound


def _empty_out_cells(cfg: QBAConfig):
    """One sender's row of the next round's mailbox."""
    slots, max_l, s = cfg.slots, cfg.max_l, cfg.size_l
    return (
        jnp.full((slots, max_l, s), SENTINEL, dtype=jnp.int32),
        jnp.zeros((slots, max_l), dtype=jnp.int32),
        jnp.zeros((slots,), dtype=jnp.int32),
        jnp.zeros((slots, s), dtype=bool),
        jnp.zeros((slots,), dtype=jnp.int32),
        jnp.zeros((slots,), dtype=bool),
    )


def _write_cell(cfg: QBAConfig, out, slot, write, p_mask, v, ev):
    """Scatter one packet into a sender row at ``slot`` where ``write``."""
    o_vals, o_lens, o_count, o_p, o_v, o_sent = out
    at = (jnp.arange(cfg.slots) == slot) & write
    return (
        jnp.where(at[:, None, None], ev.vals[None], o_vals),
        jnp.where(at[:, None], ev.lens[None], o_lens),
        jnp.where(at, ev.count, o_count),
        jnp.where(at[:, None], p_mask[None], o_p),
        jnp.where(at, v, o_v),
        o_sent | at,
    )


def step3a_one(cfg: QBAConfig, p_row, v, li):
    """One lieutenant's step 3a (``tfg.py:185-196``): receive the
    commander's packet, append own sub-list, accept + rebroadcast if
    consistent."""
    ev = append_own(empty_evidence(cfg.max_l, cfg.size_l), p_row, li)
    ok = consistent(v, ev, cfg.w)
    vi_row = (jnp.arange(cfg.w) == v) & ok
    out = _empty_out_cells(cfg)
    out = _write_cell(cfg, out, jnp.asarray(0), ok, p_row, v, ev)
    return vi_row, out


def receiver_round(cfg: QBAConfig, round_idx, key, receiver_idx, vi_row, li, mb, honest):
    """One lieutenant's inbox drain for one voting round
    (``tfg.py:337-348`` + ``lieu_receive``, ``tfg.py:289-300``).

    Fully vectorized: the reference drains its MPI queue packet by packet,
    but the only *sequential* part of that drain is the accepted-set dedup
    (``v not in Vi``, ``tfg.py:294``) and outgoing-slot allocation —
    corruption, evidence append, and the consistency verdict are
    per-packet independent.  So every packet is processed in parallel
    (``vmap`` — XLA vectorizes across packets, receivers, and trials), and
    the sequencing collapses to closed-form mask algebra in
    (sender, slot) lexicographic packet order (docs/DIVERGENCES.md D5):

    * dedup = first-occurrence-wins over a packet x packet value-match
      matrix (identical verdicts to the serial drain: two packets only
      interact when they carry the same ``v``);
    * slot allocation = exclusive prefix count of rebroadcasts.
    """
    n_s, slots = cfg.n_lieutenants, cfg.slots
    n_pk = n_s * slots

    def flat(x):
        return x.reshape((n_pk,) + x.shape[2:])

    vals_f, lens_f, count_f = flat(mb.vals), flat(mb.lens), flat(mb.count)
    p_f, v_f, sent_f = flat(mb.p_mask), flat(mb.v), flat(mb.sent)
    idxs = jnp.arange(n_pk)

    def deliver(idx):
        """Corrupt + append one mailbox cell (tfg.py:271-284,291)."""
        pk = Packet(
            p_mask=p_f[idx],
            v=v_f[idx],
            evidence=Evidence(vals=vals_f[idx], lens=lens_f[idx], count=count_f[idx]),
        )
        sender_idx = idx // slots
        cell_key = jax.random.fold_in(key, idx)
        pk, delivered = corrupt_at_delivery(cfg, cell_key, pk, honest[sender_idx + 2])
        delivered &= sent_f[idx] & (sender_idx != receiver_idx)
        delivered &= ~late_drop(cfg, cell_key)
        ev = append_own(pk.evidence, pk.p_mask, li)
        return pk, ev, delivered

    def prep(idx):
        """Per-packet verdict only (tfg.py:291-294) — scalars out, so the
        [max_l, size_l] evidence stays a fused intermediate instead of a
        materialized [n_pk, max_l, size_l] batch."""
        pk, ev, delivered = deliver(idx)
        ok = (
            delivered
            & consistent(pk.v, ev, cfg.w)
            & (ev.count == round_idx + 1)
        )
        return pk.v, ok

    v_all, ok_all = jax.vmap(prep)(idxs)

    # Acceptance with first-occurrence-wins dedup against Vi (tfg.py:294).
    cand = ok_all & ~vi_row[v_all]
    same_v_before = (
        (v_all[None, :] == v_all[:, None])
        & cand[None, :]
        & (idxs[None, :] < idxs[:, None])
    )
    acc = cand & ~jnp.any(same_v_before, axis=1)
    vi_row = vi_row | jnp.any(
        acc[:, None] & (v_all[:, None] == jnp.arange(cfg.w)[None, :]), axis=0
    )

    # Rebroadcast while round <= nDishonest (tfg.py:298-299); outgoing slot
    # = exclusive prefix count, overflow recorded past the static bound.
    rebroadcast = acc & (round_idx <= cfg.n_dishonest)
    slot = jnp.cumsum(rebroadcast.astype(jnp.int32)) - rebroadcast
    write = rebroadcast & (slot < slots)
    overflow = jnp.any(rebroadcast & ~write)

    # Scatter written packets into this sender's outgoing mailbox row.
    # Slot assignment is injective, so each slot gathers from at most one
    # packet; the <= slots written packets are re-delivered (same fold_in
    # key -> identical corruption) so only [slots, max_l, size_l] — not
    # [n_pk, ...] — is ever materialized.
    hit = write[None, :] & (slot[None, :] == jnp.arange(slots)[:, None])
    has = jnp.any(hit, axis=1)  # bool[slots]
    src = jnp.argmax(hit, axis=1)  # packet index feeding each slot

    def rebuild(idx, valid):
        pk, ev, _ = deliver(idx)
        return (
            jnp.where(valid, ev.vals, SENTINEL),
            jnp.where(valid, ev.lens, 0),
            jnp.where(valid, ev.count, 0),
            jnp.where(valid, pk.p_mask, False),
            jnp.where(valid, pk.v, 0),
        )

    out = (*jax.vmap(rebuild)(src, has), has)
    return vi_row, out, overflow


def setup_trial(cfg: QBAConfig, key: jax.Array, hints: PartitionHints | None = None):
    """Protocol phases before the round loop, shared by every engine.

    Dishonesty assignment (``tfg.py:101-125``), particle distribution
    (``tfg.py:132-163``), step 1b Q-correlated recovery + order choice
    (``tfg.py:325-329``), step 2 per-recipient packets (``tfg.py:166-184``).

    Returns ``(honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds)``.
    """
    k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
    honest = assign_dishonest(cfg, k_dis)
    lists, _qcorr = generate_lists_for(cfg, k_lists)
    if hints is not None and hints.lists is not None:
        lists = jax.lax.with_sharding_constraint(lists, hints.lists)

    is_qcorr = lists[0] != lists[1]
    v_sent, v_comm = commander_orders(cfg, k_comm, honest[1])
    p_rows = is_qcorr[None, :] & (lists[1][None, :] == v_sent[:, None])
    return honest, lists[2:], p_rows, v_sent, v_comm, k_rounds


def finish_trial(cfg: QBAConfig, vi, v_comm, honest, overflow) -> TrialResult:
    """Decision + verdict (``tfg.py:303-306,351-363``), shared by every
    engine: masked-min decisions, success oracle, result assembly."""
    lieu_decisions = jax.vmap(
        lambda row: decide_order(row, v_comm, jnp.asarray(False), cfg.w)
    )(vi)
    decisions = jnp.concatenate([v_comm[None], lieu_decisions])
    success = success_oracle(decisions, honest[1:])
    return TrialResult(
        success=success,
        decisions=decisions,
        honest=honest[1:],
        v_comm=v_comm,
        vi=vi,
        overflow=overflow,
    )


def run_trial(
    cfg: QBAConfig, key: jax.Array, hints: PartitionHints | None = None
) -> TrialResult:
    """One full protocol execution — jit-compilable, vmap-batchable."""
    honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = setup_trial(
        cfg, key, hints
    )

    # Step 3a (tfg.py:185-196), vmapped over lieutenants.
    vi, out_cells = jax.vmap(lambda p, v, li: step3a_one(cfg, p, v, li))(
        p_rows, v_sent, lieu_lists
    )
    mb = Mailbox(*out_cells)

    # Step 3b (tfg.py:337-348): synchronous rounds 1..n_dishonest+1.
    receiver_ids = jnp.arange(cfg.n_lieutenants)

    def round_body(carry, round_idx):
        vi, mb = carry
        k_round = jax.random.fold_in(k_rounds, round_idx)
        keys = jax.vmap(lambda i: jax.random.fold_in(k_round, i))(receiver_ids)
        vi, out_cells, ovf = jax.vmap(
            lambda k, r, vrow, li: receiver_round(cfg, round_idx, k, r, vrow, li, mb, honest)
        )(keys, receiver_ids, vi, lieu_lists)
        return (vi, Mailbox(*out_cells)), jnp.any(ovf)

    (vi, _), overflows = jax.lax.scan(
        round_body, (vi, mb), jnp.arange(1, cfg.n_rounds + 1)
    )
    return finish_trial(cfg, vi, v_comm, honest, jnp.any(overflows))
