"""End-to-end single-trial protocol engine.

The reference's orchestrator ``QBA`` (``tfg.py:309-363``) branches on MPI
rank; here every phase is an array op over the party axis:

* dishonesty assignment  -> honesty mask          (``tfg.py:101-125``)
* particle distribution  -> qsim generation        (``tfg.py:132-163``)
* step 1b + step 2       -> per-recipient P, v     (``tfg.py:166-184,325-329``)
* step 3a                -> vmapped first receive  (``tfg.py:185-196``)
* step 3b round loop     -> ``lax.scan`` over a dense mailbox
                            (``tfg.py:289-300,337-348``)
* decision + oracle      -> masked min + singleton check
                            (``tfg.py:303-306,351-363``)

Rounds are synchronous by construction (docs/DIVERGENCES.md D1); packet
processing order within a round is (sender, slot) lexicographic (D5).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
    adversary_ctx,
    assign_dishonest,
    commander_orders,
    corrupt_at_delivery,
    sample_attacks_round,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core import append_own, consistent, decide_order, success_oracle
from qba_tpu.core.types import SENTINEL, Evidence, Packet, empty_evidence
from qba_tpu.diagnostics import QBADemotionWarning, warn_and_record
from qba_tpu.qsim import generate_lists_for
from qba_tpu.rounds.mailbox import Mailbox


def _register_barrier_batching() -> bool:
    """Some jax builds ship ``lax.optimization_barrier`` without a vmap
    batching rule, which aborts every vmapped trial batch that reaches
    the barrier below.  The rule is trivial (the barrier is per-element
    identity: bind the batched operands, pass the batch dims through),
    so register it when missing.  Returns False when the primitive's
    internals are not reachable — the caller then skips the barrier
    (a perf hint only; semantics are unaffected)."""
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching

        prim = _lax_internal.optimization_barrier_p
        if prim not in batching.primitive_batchers:

            def _rule(args, dims, **params):
                return prim.bind(*args, **params), dims

            batching.primitive_batchers[prim] = _rule
        return True
    except Exception:
        return False


_HAVE_BARRIER_BATCHING = _register_barrier_batching()


@dataclasses.dataclass(frozen=True)
class PartitionHints:
    """Optional internal sharding constraints for :func:`run_trial`.

    Hashable (usable as a jit static argument).  ``lists`` is applied to
    the generated party-lists tensor ``[n_parties+1, size_l]`` — e.g.
    ``NamedSharding(mesh, P(None, "sp"))`` shards the position axis (the
    protocol's sequence axis, SURVEY §5) and lets XLA partition every
    positionwise op and insert the reductions ``consistent`` needs.
    """

    lists: jax.sharding.NamedSharding | None = None


@struct.dataclass
class ProtocolCounters:
    """On-device protocol counters (``cfg.collect_counters``), one set
    per trial — enough to triage divergence at the 33-party scale
    without a host-side replay (docs/OBSERVABILITY.md).

    Every field is derived purely from the accepted-set (``vi``) deltas
    the round scan already carries plus the per-round overflow flags,
    so collecting them cannot perturb the primary outputs (bit-identity
    pinned in tests/test_telemetry.py) and adds no dot operations to
    the traced paths (the KI-3 lint gate).  Round indices follow the
    protocol's 1-based numbering (``tfg.py:337``): 0 means accepted at
    step 3a, -1 means never accepted."""

    first_accept_round: jnp.ndarray  # int32[n_lieutenants, w]; -1 = never
    accept_counts: jnp.ndarray  # int32[w] — receivers that ever accepted v
    accepts_per_round: jnp.ndarray  # int32[n_rounds] — acceptances per round
    slot_high_water: jnp.ndarray  # int32 — max rebroadcasts queued by one
    # receiver in one round (vs the cfg.slots bound)
    overflow_rounds: jnp.ndarray  # bool[n_rounds] — slot overflow per round


@struct.dataclass
class TrialResult:
    """Everything rank 0 prints at the end of a run (``tfg.py:351-363``),
    plus TPU-design diagnostics."""

    success: jnp.ndarray  # bool
    decisions: jnp.ndarray  # int32[n_parties], index 0 = commander (rank 1)
    honest: jnp.ndarray  # bool[n_parties], same indexing
    v_comm: jnp.ndarray  # int32 — the commander's privately chosen order
    vi: jnp.ndarray  # bool[n_lieutenants, w] accepted-sets
    overflow: jnp.ndarray  # bool — a rebroadcast exceeded the slot bound
    counters: ProtocolCounters | None = None  # cfg.collect_counters only


def _empty_out_cells(cfg: QBAConfig):
    """One sender's row of the next round's mailbox."""
    slots, max_l, s = cfg.slots, cfg.max_l, cfg.size_l
    return (
        jnp.full((slots, max_l, s), SENTINEL, dtype=jnp.int32),
        jnp.zeros((slots, max_l), dtype=jnp.int32),
        jnp.zeros((slots,), dtype=jnp.int32),
        jnp.zeros((slots, s), dtype=bool),
        jnp.zeros((slots,), dtype=jnp.int32),
        jnp.zeros((slots,), dtype=bool),
    )


def _write_cell(cfg: QBAConfig, out, slot, write, p_mask, v, ev):
    """Scatter one packet into a sender row at ``slot`` where ``write``."""
    o_vals, o_lens, o_count, o_p, o_v, o_sent = out
    at = (jnp.arange(cfg.slots) == slot) & write
    return (
        jnp.where(at[:, None, None], ev.vals[None], o_vals),
        jnp.where(at[:, None], ev.lens[None], o_lens),
        jnp.where(at, ev.count, o_count),
        jnp.where(at[:, None], p_mask[None], o_p),
        jnp.where(at, v, o_v),
        o_sent | at,
    )


def step3a_one(cfg: QBAConfig, p_row, v, li):
    """One lieutenant's step 3a (``tfg.py:185-196``): receive the
    commander's packet, append own sub-list, accept + rebroadcast if
    consistent."""
    ev = append_own(empty_evidence(cfg.max_l, cfg.size_l), p_row, li)
    ok = consistent(v, ev, cfg.w)
    vi_row = (jnp.arange(cfg.w) == v) & ok
    out = _empty_out_cells(cfg)
    out = _write_cell(cfg, out, jnp.asarray(0), ok, p_row, v, ev)
    return vi_row, out


def receiver_round(cfg: QBAConfig, round_idx, draws, receiver_idx, vi_row, li, mb, honest):
    """One lieutenant's inbox drain for one voting round
    (``tfg.py:337-348`` + ``lieu_receive``, ``tfg.py:289-300``).

    Fully vectorized: the reference drains its MPI queue packet by packet,
    but the only *sequential* part of that drain is the accepted-set dedup
    (``v not in Vi``, ``tfg.py:294``) and outgoing-slot allocation —
    corruption, evidence append, and the consistency verdict are
    per-packet independent.  So every packet is processed in parallel
    (``vmap`` — XLA vectorizes across packets, receivers, and trials), and
    the sequencing collapses to closed-form mask algebra in
    (sender, slot) lexicographic packet order (docs/DIVERGENCES.md D5):

    * dedup = first-occurrence-wins over a packet x packet value-match
      matrix (identical verdicts to the serial drain: two packets only
      interact when they carry the same ``v``);
    * slot allocation = exclusive prefix count of rebroadcasts.
    """
    n_s, slots = cfg.n_lieutenants, cfg.slots
    n_pk = n_s * slots

    def flat(x):
        return x.reshape((n_pk,) + x.shape[2:])

    vals_f, lens_f, count_f = flat(mb.vals), flat(mb.lens), flat(mb.count)
    p_f, v_f, sent_f = flat(mb.p_mask), flat(mb.v), flat(mb.sent)
    idxs = jnp.arange(n_pk)
    attack, rand_v, late = draws  # this receiver's [n_pk] columns

    def deliver(idx):
        """Corrupt + append one mailbox cell (tfg.py:271-284,291)."""
        pk = Packet(
            p_mask=p_f[idx],
            v=v_f[idx],
            evidence=Evidence(vals=vals_f[idx], lens=lens_f[idx], count=count_f[idx]),
        )
        sender_idx = idx // slots
        pk, delivered = corrupt_at_delivery(
            cfg, (attack[idx], rand_v[idx]), pk, honest[sender_idx + 2]
        )
        delivered &= sent_f[idx] & (sender_idx != receiver_idx)
        delivered &= ~late[idx]
        ev = append_own(pk.evidence, pk.p_mask, li)
        return pk, ev, delivered

    # ---- Per-packet verdicts (tfg.py:271-284,291-294), fully batched. ----
    # Corruption is applied as *flags* over the verdict algebra, never as a
    # select on the evidence tensor: materializing post-corruption evidence
    # per (receiver, packet) costs a [trials, receivers, n_pk, max_l,
    # size_l] tensor (~2 GB/round at the headline config) that dominated
    # the loop.  All row-content reductions below read the raw mailbox,
    # which is receiver-independent — XLA hoists them out of the receiver
    # vmap — and the appended evidence is only materialized for the
    # <= slots rebuilt packets.
    max_l = cfg.max_l
    senders = idxs // slots
    biz = ~honest[senders + 2]  # [n_pk]

    dropped = biz & ((attack & DROP_BIT) != 0)  # tfg.py:274
    v2 = jnp.where(biz & ((attack & FORGE_BIT) != 0), rand_v, v_f)  # tfg.py:277
    clear_p = biz & ((attack & CLEAR_P_BIT) != 0)  # tfg.py:281
    clear_l = biz & ((attack & CLEAR_L_BIT) != 0)  # tfg.py:283
    # Forge-P (strategy="split" only): the delivered P mask is forged to
    # all-True.  Statically gated so every other strategy's arithmetic —
    # and the reference bit-identity pin — is untouched.
    use_fp = cfg.strategy == "split"
    forge_p = (
        biz & ((attack & FORGE_P_BIT) != 0)
        if use_fp
        else jnp.zeros_like(biz)
    )
    delivered = ~dropped & ~late & sent_f & (senders != receiver_idx)

    # Receiver-independent raw-mailbox reductions (shared by all receivers).
    # Row-sliced construction throughout (see the presence-plane note
    # below): full [n_pk, max_l, size_l] intermediates cost ~1.6 GB
    # materializations per round at the 33-party scale and tend to pick
    # degenerate T(1,128) layouts; per-row [n_pk, size_l] slices fuse
    # into full-width passes.
    valid_raw = jnp.arange(max_l)[None, :] < count_f[:, None]  # [n_pk, max_l]
    in_t_raw = vals_f != SENTINEL  # [n_pk, max_l, size_l]

    def _tree(rows, op):
        while len(rows) > 1:
            folded = [op(a, b) for a, b in zip(rows[0::2], rows[1::2])]
            if len(rows) % 2:
                folded.append(rows[-1])
            rows = folded
        return rows[0]

    def _in_valid_row(r):
        return in_t_raw[:, r] & valid_raw[:, r : r + 1]

    oob_raw = jnp.any(
        _tree(
            [
                _in_valid_row(r)
                & ((vals_f[:, r] > cfg.w) | (vals_f[:, r] < 0))
                for r in range(max_l)
            ],
            jnp.logical_or,
        ),
        axis=-1,
    )  # [n_pk]
    # Value-presence bit planes: bit (x & 31) of plane x >> 5 at
    # [pk, pos] iff some valid row holds value x there.  Replaces the
    # one-hot presence table whose construction broadcast a
    # [n_pk, max_l, size_l, w] compare — the dominant cost of this
    # engine at w = 64 scale (~100M bools per trial per round at the
    # 33-party config; docs/PERF.md round 3).  Exact for all queried
    # values (mailbox v < w, forged v < n_parties+1 <= w, li < w);
    # distinct values map to distinct (plane, bit) pairs, so stored
    # garbage cannot alias a query.
    n_planes = (cfg.w + 31) // 32
    pm_pos = []  # per plane: int32[n_pk, size_l]
    for p_i in range(n_planes):
        lo = 32 * p_i

        def row_bits(r, lo=lo):
            v_r = vals_f[:, r]
            in_r = _in_valid_row(r) & (v_r >= lo) & (v_r < lo + 32)
            return jnp.where(
                in_r, jnp.left_shift(jnp.int32(1), v_r & 31), 0
            )

        # Per-row construction + tree-shaped OR: building a full
        # [n_pk, max_l, size_l] bits tensor and reducing it cost two
        # ~1.6 GB materializations plus max_l serial slice+or fusions
        # per plane per round at the 33-party scale; row-sliced ops
        # stay [n_pk, size_l]-shaped and fuse into full-width passes.
        pm_pos.append(
            _tree([row_bits(r) for r in range(max_l)], jnp.bitwise_or)
        )
    def plane_bit_pos(q):  # int32[n_pk, size_l] query -> bool[n_pk, size_l]
        sel = pm_pos[0]
        for p_i in range(1, n_planes):
            sel = jnp.where((q >> 5) == p_i, pm_pos[p_i], sel)
        return (jnp.right_shift(sel, q & 31) & 1) != 0

    def plane_bit_any(q):  # int32[n_pk] query -> bool[n_pk]
        # Boolean any-reduce over the positional planes: an int32
        # bitwise-or lane reduction for a precomputed "anywhere" plane
        # lowered to a T(1,128)-layout loop costing ~370 ms/plane per
        # batch; the boolean reduce vectorizes cleanly.
        q_pos = jnp.broadcast_to(q[:, None], pm_pos[0].shape)
        return jnp.any(plane_bit_pos(q_pos), axis=-1)
    cell_lens_ok_raw = jnp.all(
        jnp.where(valid_raw, lens_f == lens_f[:, :1], True), axis=1
    )  # [n_pk]
    # Pairwise row-collision (two valid rows sharing a value at the
    # same position, tfg.py:96-98) via a popcount identity instead of
    # the [n_pk, max_l, max_l, size_l] pairwise compare (a ~17 ms/round
    # fusion at the 33-party scale): each plane-covered value
    # contributes exactly one bit, duplicates collapse under OR, so a
    # collision at a position is exactly
    # popcount(planes) < (number of plane-covered entries).  Both sides
    # count plane-covered values ONLY ([0, 32*n_planes) — a superset of
    # [0, w)), keeping the identity exact for them.  The one value this
    # test treats differently from the pairwise compare is the
    # legal-but-boundary v == w (oob tolerates `<= w`, tfg.py:93, but
    # no plane covers it when w is the usual power of two): a w-vs-w
    # collision would go unflagged.  Unreachable: evidence rows only
    # ever hold particle-list values, and the sampler draws those from
    # [0, w).  All other uncovered values (> w, < 0) set oob_raw, which
    # rejects the packet through cond2 whenever cond3 is consulted
    # (~clear_l).
    hi = 32 * n_planes

    def _covered_row(r):
        v_r = vals_f[:, r]
        return (
            _in_valid_row(r) & (v_r >= 0) & (v_r < hi)
        ).astype(jnp.int32)

    n_in_pos = _tree(
        [_covered_row(r) for r in range(max_l)], jnp.add
    )  # [n_pk, size_l]
    pop_pos = sum(
        jax.lax.population_count(pm).astype(jnp.int32) for pm in pm_pos
    )
    cells_ok_raw = ~jnp.any(pop_pos < n_in_pos, axis=-1)  # [n_pk]

    # Receiver-dependent part: the would-be own row (tfg.py:291).
    p2 = p_f & ~clear_p[:, None]  # [n_pk, size_l]
    if use_fp:
        p2 = p2 | forge_p[:, None]  # forged-full mask wins over clear
    own = jnp.where(p2, li[None, :], SENTINEL)  # [n_pk, size_l]
    s_p = jnp.sum(p_f.astype(jnp.int32), axis=-1)  # [n_pk] (hoisted)
    own_len = jnp.where(clear_p, 0, s_p)  # |own row| = (1-cp) * |P|
    if use_fp:
        own_len = jnp.where(forge_p, cfg.size_l, own_len)

    count_eff = jnp.where(clear_l, 0, count_f)
    # Dup detection (row == own).  The direct form materializes a
    # [receivers, n_pk, max_l, size_l] compare under the receiver vmap
    # — the dominant fusion of this engine at the 33-party scale
    # (~0.5 s of a 3.6 s 250-trial batch; docs/PERF.md round 4).  The
    # MXU form is the exact integer identity
    #   sum_pos (v - own)^2 == 0  <=>  row == own,
    # with own = p2*(li+1) - 1 expanded so clear_p factors out of the
    # position contraction:
    #   cross = (1-cp) * [p*v](li+1) - sum v
    #   sum own^2 = (1-cp) * [p](li^2-1) + size_l
    # and the two bracketed contractions are matmuls against this
    # receiver's li tables — under the receiver vmap XLA batches them
    # into [n_pk*max_l, size_l] @ [size_l, receivers] MXU ops.  f32 is
    # exact while size_l * w^2 < 2^24 (values live in [-1, w]); wider
    # configs keep the elementwise form.
    if cfg.size_l * cfg.w * cfg.w < 2**24:
        li_f = li.astype(jnp.float32)
        pv = jnp.where(p_f[:, None, :], vals_f, 0).astype(jnp.float32)
        # Precision.HIGHEST: the identity needs exact integer dots, and
        # a default-precision f32 dot may lower through bf16, rounding
        # operands > 256 (li^2-1 here; vals/li at w > 256) — the round-5
        # wrong-draw bug class (ops/round_kernel_tiled._prec); enforced
        # by the qba-tpu lint KI-3 pass on this traced path.
        m1 = jax.lax.dot_general(
            pv.reshape(n_pk * max_l, cfg.size_l),
            (li_f + 1.0)[:, None],
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        ).reshape(n_pk, max_l)
        m2 = jax.lax.dot_general(
            p_f.astype(jnp.float32), (li_f * li_f - 1.0)[:, None],
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        )[:, 0]
        s_v = jnp.sum(vals_f, axis=-1)  # int32, exact
        ssq_v = jnp.sum(vals_f * vals_f, axis=-1)
        cp_f = clear_p.astype(jnp.float32)[:, None]
        cross = (1.0 - cp_f) * m1 - s_v.astype(jnp.float32)
        ssq_o = (1.0 - cp_f) * m2[:, None] + float(cfg.size_l)
        if use_fp:
            # Forged-full mask: the P factor drops out of the identity —
            # one extra unmasked contraction (m1_full) and a scalar
            # (sum li^2-1) replace the masked terms where forge_p.
            fp_f = forge_p.astype(jnp.float32)[:, None]
            m1_full = jax.lax.dot_general(
                vals_f.astype(jnp.float32).reshape(
                    n_pk * max_l, cfg.size_l
                ),
                (li_f + 1.0)[:, None],
                (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            ).reshape(n_pk, max_l)
            m2_full = jnp.sum(li_f * li_f - 1.0)
            cross = fp_f * (m1_full - s_v.astype(jnp.float32)) + (
                1.0 - fp_f
            ) * cross
            ssq_o = fp_f * (m2_full + float(cfg.size_l)) + (
                1.0 - fp_f
            ) * ssq_o
        mism = ssq_v.astype(jnp.float32) - 2.0 * cross + ssq_o
        dup_rows = mism == 0.0  # [n_pk, max_l]
    else:  # pragma: no cover - w > 256-class configs
        dup_rows = jnp.all(vals_f == own[:, None, :], axis=-1)
    dup = ~clear_l & jnp.any(valid_raw & dup_rows, axis=-1)
    # append_own's fullness guard (consistent_after_append): the own-row
    # terms below apply only when the row actually enters L'.  With the
    # config invariant max_l >= n_rounds + 1 (enforced in QBAConfig),
    # count_eff <= max_l - 1 always, so `appended` reduces to `~dup` —
    # but the guard keeps every engine on the spec even if the bound is
    # ever raised/decoupled via max_evidence_rows.
    appended = ~dup & (count_eff < max_l)
    new_count = jnp.where(appended, count_eff + 1, count_eff)

    # Cond 1 (tfg.py:88-92).
    cond1 = (clear_l | cell_lens_ok_raw) & (
        ~appended | (count_eff == 0) | (own_len == lens_f[:, 0])
    )
    # Cond 2 (tfg.py:93-94): v2 < w always (mailbox v < w; rand_v < n+1 <= w).
    bad_cell = ~clear_l & (oob_raw | plane_bit_any(v2))
    bad_own = appended & jnp.any(
        p2 & ((own == v2[:, None]) | (own > cfg.w) | (own < 0)), axis=-1
    )
    cond2 = ~(bad_cell | bad_own)
    # Cond 3 (tfg.py:96-98): cell pairs, and own vs cells when appended —
    # the own-row collision via the per-position presence planes (one
    # [n_pk, size_l] op instead of max_l of them; own == li on every
    # p2 position, and the planes already fold in valid/in-tuple).
    li_q = jnp.broadcast_to(li[None, :].astype(jnp.int32), p2.shape)
    own_collides = jnp.any(p2 & plane_bit_pos(li_q), axis=-1)
    cond3 = (clear_l | cells_ok_raw) & (~appended | ~(~clear_l & own_collides))

    v_all = v2
    ok_all = delivered & cond1 & cond2 & cond3 & (new_count == round_idx + 1)
    # Pin the per-packet flags as materialized values: without the barrier
    # XLA fuses the [max_l, size_l] reductions above into every consumer,
    # recomputing them per use (three ~70 ms loop fusions at the headline
    # config).
    if _HAVE_BARRIER_BATCHING:
        v_all, ok_all = jax.lax.optimization_barrier((v_all, ok_all))

    # Acceptance with first-occurrence-wins dedup against Vi (tfg.py:294):
    # for each order value, only the first candidate packet carrying it
    # is accepted — O(w * n_pk) one-hot algebra, not an n_pk x n_pk
    # matrix, and no advanced indexing: the previous `vi_row[v_all]` /
    # `first_idx[v_all]` per-element gathers lowered to serialized TPU
    # gather loops that dominated the whole engine at scale (2 x ~2.2 s
    # of a 7.9 s 33-party batch; docs/PERF.md round 3).  This one-hot
    # formulation is also the differential oracle for the kernels'
    # round-6 parallel first-accept reduction
    # (ops/verdict_algebra.py accept_first_per_value_all): the engine
    # equivalence suites pin the batched all-receiver dedup against this
    # per-receiver sequential walk bit for bit, so KEEP this code
    # independent of the kernels' shared helpers.
    onehot_v = v_all[:, None] == jnp.arange(cfg.w)[None, :]  # [n_pk, w]
    cand = ok_all & ~jnp.any(onehot_v & vi_row[None, :], axis=1)
    cand_idx = jnp.where(cand, idxs, n_pk)
    first_idx = jnp.min(
        jnp.where(onehot_v, cand_idx[:, None], n_pk), axis=0
    )  # [w] — first candidate index per value
    first_b = jnp.min(
        jnp.where(onehot_v, first_idx[None, :], n_pk), axis=1
    )  # [n_pk] — that index, spread back per packet
    acc = cand & (first_b == idxs)
    vi_row = vi_row | jnp.any(acc[:, None] & onehot_v, axis=0)

    # Rebroadcast while round <= nDishonest (tfg.py:298-299); outgoing slot
    # = exclusive prefix count, overflow recorded past the static bound.
    rebroadcast = acc & (round_idx <= cfg.n_dishonest)
    slot = jnp.cumsum(rebroadcast.astype(jnp.int32)) - rebroadcast
    write = rebroadcast & (slot < slots)
    overflow = jnp.any(rebroadcast & ~write)

    # Scatter written packets into this sender's outgoing mailbox row.
    # Slot assignment is injective, so each slot gathers from at most one
    # packet; the <= slots written packets are re-delivered (indexing the
    # same shared draw arrays -> identical corruption) so only
    # [slots, max_l, size_l] — not [n_pk, ...] — is ever materialized.
    hit = write[None, :] & (slot[None, :] == jnp.arange(slots)[:, None])
    has = jnp.any(hit, axis=1)  # bool[slots]
    src = jnp.argmax(hit, axis=1)  # packet index feeding each slot

    def rebuild(idx, valid):
        pk, ev, _ = deliver(idx)
        return (
            jnp.where(valid, ev.vals, SENTINEL),
            jnp.where(valid, ev.lens, 0),
            jnp.where(valid, ev.count, 0),
            jnp.where(valid, pk.p_mask, False),
            jnp.where(valid, pk.v, 0),
        )

    out = (*jax.vmap(rebuild)(src, has), has)
    return vi_row, out, overflow


def setup_trial(cfg: QBAConfig, key: jax.Array, hints: PartitionHints | None = None):
    """Protocol phases before the round loop, shared by every engine.

    Dishonesty assignment (``tfg.py:101-125``), particle distribution
    (``tfg.py:132-163``), step 1b Q-correlated recovery + order choice
    (``tfg.py:325-329``), step 2 per-recipient packets (``tfg.py:166-184``).

    Returns ``(honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds)``.
    """
    k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
    honest = assign_dishonest(cfg, k_dis)
    lists, _qcorr = generate_lists_for(cfg, k_lists)
    if hints is not None and hints.lists is not None:
        lists = jax.lax.with_sharding_constraint(lists, hints.lists)

    is_qcorr = lists[0] != lists[1]
    v_sent, v_comm = commander_orders(cfg, k_comm, honest[1])
    p_rows = is_qcorr[None, :] & (lists[1][None, :] == v_sent[:, None])
    return honest, lists[2:], p_rows, v_sent, v_comm, k_rounds


def finish_trial(
    cfg: QBAConfig, vi, v_comm, honest, overflow, counters=None
) -> TrialResult:
    """Decision + verdict (``tfg.py:303-306,351-363``), shared by every
    engine: masked-min decisions, success oracle, result assembly."""
    lieu_decisions = jax.vmap(
        lambda row: decide_order(row, v_comm, jnp.asarray(False), cfg.w)
    )(vi)
    decisions = jnp.concatenate([v_comm[None], lieu_decisions])
    success = success_oracle(decisions, honest[1:])
    return TrialResult(
        success=success,
        decisions=decisions,
        honest=honest[1:],
        v_comm=v_comm,
        vi=vi,
        overflow=overflow,
        counters=counters,
    )


# ---------------------------------------------------------------------------
# Protocol counters (cfg.collect_counters): all state is a pure function
# of the vi carry the round scans already thread, so wrapping a round
# body with scan_rounds(collect=True) cannot change what the body
# computes — the collect=False path is byte-for-byte the original scan.


def _vi_bool(vi):
    """Engines carry vi as bool (XLA) or int32 (kernel paths)."""
    return vi if vi.dtype == jnp.bool_ else vi != 0


def counters_init(cfg: QBAConfig, vi0):
    """Counter scan state from the step-3a accepted-sets: first-accept
    rounds (0 = step 3a, -1 = pending) and the slot high-water mark.
    ``vi0`` is bool[..., n_receivers, w]; leading axes (trial packing)
    broadcast through."""
    first_accept = jnp.where(vi0, jnp.int32(0), jnp.int32(-1))
    high_water = jnp.zeros(vi0.shape[:-2], jnp.int32)
    return first_accept, high_water


def counters_step(cfg: QBAConfig, state, vi_old, vi_new, round_idx):
    """Fold one round's acceptance delta into the counter state.

    ``newly`` is exactly the set of (receiver, value) pairs accepted
    this round; while ``round <= n_dishonest`` each acceptance queues a
    rebroadcast (``tfg.py:298-299``), so the per-receiver newly-accepted
    count IS the number of outgoing slots the receiver claimed — its
    max over receivers/rounds is the slot high-water mark the
    ``cfg.slots`` bound is sized against."""
    first_accept, high_water = state
    newly = vi_new & ~vi_old
    r = jnp.asarray(round_idx, jnp.int32)
    first_accept = jnp.where(newly, r, first_accept)
    per_receiver = jnp.sum(newly, axis=-1, dtype=jnp.int32)
    queued = jnp.where(r <= cfg.n_dishonest, per_receiver, 0)
    high_water = jnp.maximum(high_water, jnp.max(queued, axis=-1))
    accepts = jnp.sum(per_receiver, axis=-1)
    return (first_accept, high_water), accepts


def counters_finish(
    cfg: QBAConfig, state, vi_final, accepts_per_round, overflow_rounds
) -> ProtocolCounters:
    first_accept, high_water = state
    return ProtocolCounters(
        first_accept_round=first_accept,
        accept_counts=jnp.sum(vi_final, axis=-2, dtype=jnp.int32),
        accepts_per_round=accepts_per_round,
        slot_high_water=high_water,
        overflow_rounds=overflow_rounds,
    )


def scan_rounds(cfg: QBAConfig, round_body, init):
    """The shared round loop: ``lax.scan`` of ``round_body`` over
    voting rounds ``1..n_rounds`` (``tfg.py:337``).

    Every engine's round body carries ``(vi, <engine state>)`` and
    emits a per-round overflow flag; that shared shape is what lets the
    counters ride ANY engine without touching its kernels.  With
    ``cfg.collect_counters`` the body is wrapped to also thread the
    :class:`ProtocolCounters` state (computed from the vi delta around
    the body); without it the original scan runs unchanged.

    Engines whose round loop runs IN-KERNEL (the trial megakernel,
    ``round_engine="pallas_mega"``) have no host scan for this wrapper
    to instrument: requesting counters on a scan-free engine is DEFINED
    as a recorded demotion to the fused per-round engine
    (:func:`_demote_mega` emits the :class:`QBADemotionWarning`), whose
    counters are bit-identical because every engine's per-round vi
    sequence is (tests/test_trial_megakernel.py).

    Returns ``(carry, overflow_stack, counter_state_or_None)``."""
    rounds = jnp.arange(1, cfg.n_rounds + 1)
    if not cfg.collect_counters:
        carry, overflows = jax.lax.scan(round_body, init, rounds)
        return carry, overflows, None

    state0 = counters_init(cfg, _vi_bool(init[0]))

    def body(carry, round_idx):
        inner, state = carry
        vi_old = _vi_bool(inner[0])
        inner, ovf = round_body(inner, round_idx)
        state, accepts = counters_step(
            cfg, state, vi_old, _vi_bool(inner[0]), round_idx
        )
        return (inner, state), (ovf, accepts)

    (carry, state), (overflows, accepts) = jax.lax.scan(
        body, (init, state0), rounds
    )
    return carry, overflows, (state, accepts)


def _finish_counters(cfg: QBAConfig, counter_state, vi_final, overflows):
    """Counter state + stacked per-round overflow -> ProtocolCounters
    (None passthrough when counters are off).  ``overflows`` may be
    bool[n_rounds] (XLA/pallas) or a per-round int grid (tiled/fused
    kernels) — normalized to a per-round bool here."""
    if counter_state is None:
        return None
    state, accepts = counter_state
    per_round = jnp.any(
        jnp.reshape(_vi_bool(overflows), (cfg.n_rounds, -1)), axis=1
    )
    return counters_finish(cfg, state, vi_final, accepts, per_round)


def run_rounds_xla(cfg: QBAConfig, vi, mb, lieu_lists, honest, k_rounds,
                   ctx=None):
    """Step 3b (tfg.py:337-348) as pure XLA: ``lax.scan`` over rounds,
    receivers vmapped.  Portable to any backend."""
    receiver_ids = jnp.arange(cfg.n_lieutenants)

    def round_body(carry, round_idx):
        vi, mb = carry
        k_round = jax.random.fold_in(k_rounds, round_idx)
        draws = sample_attacks_round(
            cfg, k_round, round_idx, ctx
        )  # each [n_pk, n_lieu]
        vi, out_cells, ovf = jax.vmap(
            lambda d, r, vrow, li: receiver_round(cfg, round_idx, d, r, vrow, li, mb, honest),
            in_axes=(1, 0, 0, 0),
        )(draws, receiver_ids, vi, lieu_lists)
        return (vi, Mailbox(*out_cells)), jnp.any(ovf)

    (vi, _), overflows, cst = scan_rounds(cfg, round_body, (vi, mb))
    return vi, jnp.any(overflows), _finish_counters(cfg, cst, vi, overflows)


def run_rounds_pallas(
    cfg: QBAConfig, vi, mb, lieu_lists, honest, k_rounds, ctx=None,
    *, interpret: bool,
):
    """Step 3b on the fused Pallas round kernel
    (:func:`qba_tpu.ops.round_kernel.build_round_step`): one kernel per
    round per trial, mailbox in VMEM, packets in sublanes.  Bit-identical
    verdicts to :func:`run_rounds_xla` (tests/test_round_kernel.py)."""
    from qba_tpu.ops.round_kernel import (
        build_round_step,
        honest_packets,
        pack_mailbox,
    )

    step = build_round_step(cfg, interpret=interpret)
    n_s, slots, max_l, s = cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l
    n_pk = n_s * slots
    honest_pk = honest_packets(honest, cfg)  # [n_pk, 1]

    def pack(mb):
        return pack_mailbox(mb, n_pk, max_l, s)

    def round_body(carry, round_idx):
        vi_i32, packed = carry
        k_round = jax.random.fold_in(k_rounds, round_idx)
        attack, rand_v, late = sample_attacks_round(
            cfg, k_round, round_idx, ctx
        )
        out = step(
            round_idx, *packed, lieu_lists, vi_i32, honest_pk,
            attack.astype(jnp.int32), rand_v.astype(jnp.int32),
            late.astype(jnp.int32),
        )
        new_packed, vi_i32, ovf = out[:6], out[6], out[7]
        return (vi_i32, tuple(new_packed)), ovf[0, 0] > 0

    init = (vi.astype(jnp.int32), pack(mb))
    (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
    vi = vi_i32 != 0
    return vi, jnp.any(overflows), _finish_counters(cfg, cst, vi, overflows)


def run_rounds_tiled(
    cfg: QBAConfig, vi, out_cells, lieu_lists, honest, k_rounds, ctx=None,
    *, interpret: bool,
):
    """Step 3b on the packet-tiled engine
    (:mod:`qba_tpu.ops.round_kernel_tiled`): blocked Pallas verdict
    kernel over a compacted packet pool + XLA rebuild.  Lossless at
    scales the monolithic kernel cannot compile (33-party ``slots=w``,
    the reference's sizeL=1000); bit-identical verdicts to
    :func:`run_rounds_xla` (tests/test_round_kernel_tiled.py)."""
    from qba_tpu.ops.round_kernel_tiled import (
        META_CELL,
        build_rebuild_kernel,
        build_verdict_kernel,
        honest_cells as honest_cells_fn,
        make_verdict_tables,
        pool_from_step3a,
        rebuild_pool,
        resolve_rebuild_block,
        resolve_tiled_block,
        resolve_verdict_variant,
    )

    variant = resolve_verdict_variant(cfg)
    blk = resolve_tiled_block(cfg)
    verdict = build_verdict_kernel(
        cfg, blk, interpret=interpret, variant=variant
    )
    blk_d = resolve_rebuild_block(cfg)
    rebuild_k = (
        build_rebuild_kernel(cfg, blk_d, interpret=interpret)
        if blk_d is not None
        else None
    )
    pool = pool_from_step3a(cfg, out_cells)
    honest_cells = honest_cells_fn(honest, cfg)
    # The all-receiver variant consumes per-receiver tables instead of
    # li — round-invariant, so built once here, outside the scan.
    li_arg = (
        make_verdict_tables(cfg, lieu_lists)
        if variant == "allrecv"
        else lieu_lists
    )

    def round_body(carry, round_idx):
        vi_i32, pool = carry
        k_round = jax.random.fold_in(k_rounds, round_idx)
        attack, rand_v, late = sample_attacks_round(
            cfg, k_round, round_idx, ctx
        )
        # Draws stay mailbox-cell-ordered — both kernels select each
        # pool entry's row in-kernel by its cell id (one-hot MXU), so
        # the randomness keeps its identity without XLA-side gathers.
        att_c = attack.astype(jnp.int32)
        rv_c = rand_v.astype(jnp.int32)
        acc, vi_i32 = verdict(
            round_idx, *pool, li_arg, vi_i32,
            honest_cells, att_c, rv_c, late.astype(jnp.int32),
        )
        if rebuild_k is not None:
            pool_new, ovf = rebuild_k(
                round_idx, *pool, lieu_lists, acc, att_c, rv_c,
                honest_cells,
            )
        else:
            # The XLA fallback consumes pool-ordered draws.
            cell = pool[3][:, META_CELL]
            pool_new, ovf = rebuild_pool(
                cfg, round_idx, pool, lieu_lists, acc,
                jnp.take(att_c, cell, axis=0),
                jnp.take(rv_c, cell, axis=0),
                jnp.take(honest_cells, cell, axis=0),
            )
        return (vi_i32, pool_new), ovf

    init = (vi.astype(jnp.int32), pool)
    (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
    vi = vi_i32 != 0
    return vi, jnp.any(overflows), _finish_counters(cfg, cst, vi, overflows)


def run_rounds_fused(
    cfg: QBAConfig, vi, out_cells, lieu_lists, honest, k_rounds, ctx=None,
    *, interpret: bool,
):
    """Step 3b on the FUSED round engine
    (:func:`qba_tpu.ops.round_kernel_tiled.build_fused_round_kernel`):
    verdict + rebuild in ONE ``pallas_call`` per round — no
    intermediate ``acc``/``vi`` HBM materialization, half the launches
    of :func:`run_rounds_tiled`.  Bit-identical to the two-kernel path
    and the XLA oracle (tests/test_round_kernel_fused.py); demotes to
    :func:`run_rounds_tiled` with a warning where the fused kernel
    doesn't compile."""
    from qba_tpu.ops.round_kernel_tiled import (
        build_fused_round_kernel,
        honest_cells as honest_cells_fn,
        make_verdict_tables,
        pool_from_step3a,
        resolve_fused_block,
        resolve_tiled_block,
        resolve_verdict_variant,
    )

    variant = resolve_verdict_variant(cfg)
    blk_v = resolve_tiled_block(cfg)
    blk_d = resolve_fused_block(cfg)
    if blk_d is None:
        warn_and_record(
            "fused round kernel unavailable at (n_parties="
            f"{cfg.n_parties}, size_l={cfg.size_l}, slots={cfg.slots});"
            " demoting to the two-kernel tiled path",
            QBADemotionWarning,
            site="rounds.engine.run_rounds_fused",
            stacklevel=2,
            engine_from="pallas_fused",
            engine_to="pallas_tiled",
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            slots=cfg.slots,
        )
        return run_rounds_tiled(
            cfg, vi, out_cells, lieu_lists, honest, k_rounds, ctx,
            interpret=interpret,
        )
    fused = build_fused_round_kernel(
        cfg, blk_d, blk_v, interpret=interpret, variant=variant
    )
    pool = pool_from_step3a(cfg, out_cells)
    honest_cells = honest_cells_fn(honest, cfg)
    li_arg = (
        make_verdict_tables(cfg, lieu_lists)
        if variant == "allrecv"
        else lieu_lists
    )

    def round_body(carry, round_idx):
        vi_i32, pool = carry
        k_round = jax.random.fold_in(k_rounds, round_idx)
        attack, rand_v, late = sample_attacks_round(
            cfg, k_round, round_idx, ctx
        )
        pool_new, vi_i32, ovf = fused(
            round_idx, *pool, lieu_lists, li_arg, vi_i32,
            honest_cells, attack.astype(jnp.int32),
            rand_v.astype(jnp.int32), late.astype(jnp.int32),
        )
        return (vi_i32, tuple(pool_new)), ovf

    init = (vi.astype(jnp.int32), pool)
    (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
    vi = vi_i32 != 0
    return vi, jnp.any(overflows), _finish_counters(cfg, cst, vi, overflows)


def run_trials_fused_packed(cfg: QBAConfig, keys, pack: int):
    """Batched fused-engine runner with TRIAL PACKING: ``pack`` trials
    fold into one kernel grid (a leading ``k`` axis on every
    trial-varying operand), so the per-grid-step fixed overhead that
    dominates small configs amortizes ``pack``-fold (docs/PERF.md
    round 7).  The batch vmaps over ``trials // pack`` GROUPS whose
    round scan calls the packed fused kernel once per round.

    Trials stay independent — setup, attack draws, and the finish pass
    are per-trial (the kernel touches only slice ``t`` of every
    trial-varying ref) — so results are bit-identical to the unpacked
    path trial for trial (tests/test_round_kernel_fused.py).

    Requires ``pack`` to divide the batch; the caller
    (:func:`qba_tpu.backends.jax_backend.run_trials`) falls back to the
    plain vmap path otherwise.  Returns the per-trial
    :class:`TrialResult` batch (leading axis = trials)."""
    from qba_tpu.ops.round_kernel_tiled import (
        build_fused_round_kernel,
        honest_cells as honest_cells_fn,
        make_verdict_tables,
        pool_from_step3a,
        resolve_fused_block,
        resolve_tiled_block,
        resolve_verdict_variant,
    )

    interpret = jax.default_backend() != "tpu"
    variant = resolve_verdict_variant(cfg)
    blk_v = resolve_tiled_block(cfg)
    blk_d = resolve_fused_block(cfg, trial_pack=pack)
    if blk_d is None or pack < 2:
        # No packed plan — the plain per-trial vmap path handles it.
        return jax.vmap(lambda k: run_trial(cfg, k))(keys)
    fused = build_fused_round_kernel(
        cfg, blk_d, blk_v, interpret=interpret, variant=variant,
        trial_pack=pack,
    )
    n_groups = keys.shape[0] // pack

    def setup_one(key):
        honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = (
            setup_trial(cfg, key, None)
        )
        vi, out_cells = jax.vmap(
            lambda p, v, li: step3a_one(cfg, p, v, li)
        )(p_rows, v_sent, lieu_lists)
        pool = pool_from_step3a(cfg, out_cells)
        li_arg = (
            make_verdict_tables(cfg, lieu_lists)
            if variant == "allrecv"
            else lieu_lists
        )
        return (
            honest, lieu_lists, li_arg, v_comm, k_rounds,
            vi.astype(jnp.int32), pool,
            honest_cells_fn(honest, cfg),
            adversary_ctx(cfg, k_rounds, v_sent),
        )

    (honest_t, li_t, li_arg_t, v_comm_t, k_rounds_t, vi_t, pool_t,
     hc_t, ctx_t) = jax.vmap(setup_one)(keys)

    def group(x):  # [trials, ...] -> [n_groups, pack, ...]
        return jax.tree_util.tree_map(
            lambda a: a.reshape((n_groups, pack) + a.shape[1:]), x
        )

    def run_group(li_k, li_arg_k, k_rounds_k, vi_k, pool_k, hc_k, ctx_k):
        vals, lens, p, meta = pool_k
        # The kernel's packed vals layout is [max_l, k, cap, s].
        vals = jnp.moveaxis(vals, 0, 1)

        def round_body(carry, round_idx):
            vi_k, pool = carry
            att, rv, late = jax.vmap(
                lambda kr, cx: sample_attacks_round(
                    cfg, jax.random.fold_in(kr, round_idx),
                    round_idx, cx,
                )
            )(k_rounds_k, ctx_k)
            pool_new, vi_k, ovf = fused(
                round_idx, *pool, li_k, li_arg_k, vi_k, hc_k,
                att.astype(jnp.int32), rv.astype(jnp.int32),
                late.astype(jnp.int32),
            )
            return (vi_k, tuple(pool_new)), ovf

        init = (vi_k, (vals, lens, p, meta))
        (vi_k, _), ovfs, cst = scan_rounds(cfg, round_body, init)
        vi_b = vi_k != 0  # [k, n_rv, w]
        counters = None
        if cst is not None:
            # Packed layout: counter state carries the leading [k] trial
            # axis (counters_init/step broadcast through it); the
            # per-round scan outputs stack rounds FIRST, so move the
            # trial axis out front before assembling per-trial counters.
            state, accepts = cst  # accepts [n_rounds, k]
            per_round = jnp.any(
                jnp.reshape(
                    _vi_bool(ovfs), (cfg.n_rounds, pack, -1)
                ),
                axis=-1,
            )  # [n_rounds, k]
            counters = counters_finish(
                cfg, state, vi_b,
                jnp.moveaxis(accepts, 0, 1),
                jnp.moveaxis(per_round, 0, 1),
            )
        return vi_b, jnp.any(ovfs, axis=0), counters  # [k,n_rv,w], [k]

    vi_g, ovf_g, cnt_g = jax.vmap(run_group)(
        group(li_t), group(li_arg_t), group(k_rounds_t),
        group(vi_t), group(pool_t), group(hc_t), group(ctx_t),
    )
    vi_flat = vi_g.reshape((keys.shape[0],) + vi_g.shape[2:])
    ovf_flat = ovf_g.reshape((keys.shape[0],))
    cnt_flat = jax.tree_util.tree_map(
        lambda a: a.reshape((keys.shape[0],) + a.shape[2:]), cnt_g
    )
    return jax.vmap(
        lambda vi, vc, h, o, c: finish_trial(cfg, vi, vc, h, o, c)
    )(vi_flat, v_comm_t, honest_t, ovf_flat, cnt_flat)


def _demote_mega(cfg: QBAConfig) -> str | None:
    """Why the trial megakernel cannot run this config, as the engine
    it demotes to (None = no demotion, run the megakernel).

    Two recorded reasons: ``collect_counters`` needs the host round
    scan the megakernel eliminates (the :func:`scan_rounds` seam —
    counters on the demoted fused path are bit-identical), and a
    missing plan from :func:`resolve_mega_block` (VMEM budget or
    compile probe refused the one-launch kernel)."""
    from qba_tpu.ops.round_kernel_tiled import resolve_mega_block

    if cfg.collect_counters:
        warn_and_record(
            "trial megakernel has no host round scan for the counters "
            "wrapper to instrument; collect_counters demotes to the "
            "fused per-round engine (bit-identical counters)",
            QBADemotionWarning,
            site="rounds.engine.run_trial",
            stacklevel=3,
            engine_from="pallas_mega",
            engine_to="pallas_fused",
            reason="counters_need_host_scan",
        )
        return "pallas_fused"
    if resolve_mega_block(cfg) is None:
        warn_and_record(
            "trial megakernel unavailable at (n_parties="
            f"{cfg.n_parties}, size_l={cfg.size_l}, slots={cfg.slots});"
            " demoting to the fused per-round engine",
            QBADemotionWarning,
            site="rounds.engine.run_trial",
            stacklevel=3,
            engine_from="pallas_mega",
            engine_to="pallas_fused",
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            slots=cfg.slots,
        )
        return "pallas_fused"
    return None


def _resolve_mega_gen_recorded(cfg: QBAConfig, trial_pack: int = 1) -> str:
    """:func:`~qba_tpu.ops.round_kernel_tiled.resolve_mega_gen` with
    the demotion discipline applied: a FORCED ``mega_gen='gf2'`` the
    planner cannot honor records a :class:`QBADemotionWarning` (the
    megakernel itself still runs — generation falls back to the host
    sampler, bit-identical by the shared-sweep construction).  ``auto``
    resolving to host is a plan, not a demotion, and stays silent."""
    from qba_tpu.ops.round_kernel_tiled import resolve_mega_gen

    mode = resolve_mega_gen(cfg, trial_pack)
    if mode == "host" and cfg.mega_gen == "gf2":
        # Config validation already pins qsim_path == "stabilizer" for
        # a forced gf2, so the only refusal left is a missing plan.
        reason = "gen_fused_plan_refused"
        warn_and_record(
            "mega_gen='gf2' forced but the gen-fused megakernel plan "
            f"is unavailable at (n_parties={cfg.n_parties}, "
            f"size_l={cfg.size_l}, total_qubits={cfg.total_qubits}); "
            "demoting step-1 generation to the host sampler (the trial"
            " megakernel itself still runs)",
            QBADemotionWarning,
            site="rounds.engine.run_trial",
            stacklevel=3,
            engine_from="pallas_mega+gen",
            engine_to="pallas_mega",
            reason=reason,
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            total_qubits=cfg.total_qubits,
        )
    return mode


def _mega_gen_setup(cfg: QBAConfig, key: jax.Array):
    """Pre-kernel phases of a gen-fused trial: the same key tree as
    :func:`setup_trial` (``k_dis, k_lists, k_comm, k_rounds``), but
    ``k_lists`` feeds :func:`stabilizer_gen_operands` — the sampler's
    host-side draws — instead of materializing the lists themselves.
    The tableau sweep and list decode then run inside the megakernel's
    VMEM prologue, bit-identically (shared ``gf2_measure_sweep``)."""
    from qba_tpu.qsim.protocol_circuits import stabilizer_gen_operands

    k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
    honest = assign_dishonest(cfg, k_dis)
    gen_ops = stabilizer_gen_operands(cfg, k_lists)
    v_sent, v_comm = commander_orders(cfg, k_comm, honest[1])
    return honest, gen_ops, v_sent, v_comm, k_rounds


def _stacked_draws(cfg: QBAConfig, k_rounds, ctx):
    """All rounds' attack draws, stacked round-major
    (``[n_rounds, n_pool, n_rv]`` int32 each) for the in-kernel loop.

    The per-round key is ``fold_in(k_rounds, round_idx)`` — the exact
    expression the scanning engines evaluate with a traced
    ``round_idx`` — so the stacked slabs are bit-identical to the
    per-round draws the fused engine consumes."""
    draws = [
        sample_attacks_round(
            cfg, jax.random.fold_in(k_rounds, r), r, ctx
        )
        for r in range(1, cfg.n_rounds + 1)
    ]
    return tuple(
        jnp.stack(x).astype(jnp.int32) for x in zip(*draws)
    )


def run_trial_mega(
    cfg: QBAConfig, key: jax.Array, hints: PartitionHints | None = None
) -> TrialResult:
    """One full protocol execution on the TRIAL MEGAKERNEL
    (:func:`qba_tpu.ops.trial_megakernel.build_trial_megakernel`): the
    step-3a particle decode, the whole ``n_rounds`` loop, and the final
    decision reduce run in ONE ``pallas_call`` — vi/acc/mailbox state
    never round-trips HBM between rounds, and the only launches left
    per trial are this kernel plus the setup/qsim ops.  Bit-identical
    to :func:`run_trial` on every other engine for identical keys
    (tests/test_trial_megakernel.py).  The caller
    (:func:`run_trial`) has already established the plan exists via
    :func:`_demote_mega`."""
    from qba_tpu.ops.round_kernel_tiled import (
        honest_cells as honest_cells_fn,
        make_verdict_tables,
        resolve_mega_block,
        resolve_verdict_variant,
    )
    from qba_tpu.ops.trial_megakernel import build_trial_megakernel

    variant = resolve_verdict_variant(cfg)
    gen = _resolve_mega_gen_recorded(cfg) == "gf2"
    if gen:
        honest, gen_ops, v_sent, v_comm, k_rounds = _mega_gen_setup(
            cfg, key
        )
        blk_d, blk_v = resolve_mega_block(cfg)
        mega = build_trial_megakernel(
            cfg, blk_d, blk_v,
            interpret=jax.default_backend() != "tpu", variant=variant,
            gen=True,
        )
        ctx = adversary_ctx(cfg, k_rounds, v_sent)
        att, rv, late = _stacked_draws(cfg, k_rounds, ctx)
        vi_i32, dec, overflow = mega(
            gen_ops, v_sent, honest_cells_fn(honest, cfg),
            att, rv, late,
        )
    else:
        honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = (
            setup_trial(cfg, key, hints)
        )
        blk_d, blk_v = resolve_mega_block(cfg)
        mega = build_trial_megakernel(
            cfg, blk_d, blk_v,
            interpret=jax.default_backend() != "tpu", variant=variant,
        )
        ctx = adversary_ctx(cfg, k_rounds, v_sent)
        att, rv, late = _stacked_draws(cfg, k_rounds, ctx)
        li_arg = (
            make_verdict_tables(cfg, lieu_lists)
            if variant == "allrecv"
            else lieu_lists
        )
        vi_i32, dec, overflow = mega(
            p_rows, lieu_lists, li_arg, v_sent,
            honest_cells_fn(honest, cfg), att, rv, late,
        )
    # The kernel's exit reduce IS decide_order's lieutenant branch
    # (masked min over accepted values, w when empty), so the finish
    # pass needs no vmapped reduce of its own.
    decisions = jnp.concatenate([v_comm[None], dec])
    return TrialResult(
        success=success_oracle(decisions, honest[1:]),
        decisions=decisions,
        honest=honest[1:],
        v_comm=v_comm,
        vi=vi_i32 != 0,
        overflow=overflow,
        counters=None,
    )


def run_trials_mega_packed(cfg: QBAConfig, keys, pack: int):
    """Batched megakernel runner with TRIAL PACKING — the megakernel
    analogue of :func:`run_trials_fused_packed`: ``pack`` trials fold
    into one launch (a leading ``k`` axis on every trial-varying
    operand), bit-identical to the unpacked path trial for trial.
    Falls back to the plain per-trial vmap (whose :func:`run_trial`
    dispatch handles demotion) when no packed plan exists or counters
    are requested."""
    from qba_tpu.ops.round_kernel_tiled import (
        honest_cells as honest_cells_fn,
        make_verdict_tables,
        resolve_mega_block,
        resolve_verdict_variant,
    )
    from qba_tpu.ops.trial_megakernel import build_trial_megakernel

    variant = resolve_verdict_variant(cfg)
    plan = resolve_mega_block(cfg, trial_pack=pack)
    if cfg.collect_counters or plan is None or pack < 2:
        return jax.vmap(lambda k: run_trial(cfg, k))(keys)
    gen = _resolve_mega_gen_recorded(cfg, trial_pack=pack) == "gf2"
    mega = build_trial_megakernel(
        cfg, *plan, interpret=jax.default_backend() != "tpu",
        variant=variant, trial_pack=pack, gen=gen,
    )
    n_groups = keys.shape[0] // pack

    if gen:

        def setup_one(key):
            honest, gen_ops, v_sent, v_comm, k_rounds = (
                _mega_gen_setup(cfg, key)
            )
            ctx = adversary_ctx(cfg, k_rounds, v_sent)
            att, rv, late = _stacked_draws(cfg, k_rounds, ctx)
            return (
                honest, gen_ops, v_sent, v_comm,
                honest_cells_fn(honest, cfg), att, rv, late,
            )
    else:

        def setup_one(key):
            honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = (
                setup_trial(cfg, key, None)
            )
            li_arg = (
                make_verdict_tables(cfg, lieu_lists)
                if variant == "allrecv"
                else lieu_lists
            )
            ctx = adversary_ctx(cfg, k_rounds, v_sent)
            att, rv, late = _stacked_draws(cfg, k_rounds, ctx)
            return (
                honest, lieu_lists, li_arg, p_rows, v_sent, v_comm,
                honest_cells_fn(honest, cfg), att, rv, late,
            )

    def group(x):  # [trials, ...] -> [n_groups, pack, ...]
        return jax.tree_util.tree_map(
            lambda a: a.reshape((n_groups, pack) + a.shape[1:]), x
        )

    def stack_rounds(att_k, rv_k, late_k):
        # The kernel's packed draw layout is round-major:
        # [n_rounds, k, n_pool, n_rv].
        return (
            jnp.moveaxis(a, 0, 1) for a in (att_k, rv_k, late_k)
        )

    if gen:
        (honest_t, gen_ops_t, v_sent_t, v_comm_t, hc_t,
         att_t, rv_t, late_t) = jax.vmap(setup_one)(keys)

        def run_group(gen_ops_k, v_k, hc_k, att_k, rv_k, late_k):
            att_k, rv_k, late_k = stack_rounds(att_k, rv_k, late_k)
            return mega(gen_ops_k, v_k, hc_k, att_k, rv_k, late_k)

        vi_g, dec_g, ovf_g = jax.vmap(run_group)(
            group(gen_ops_t), group(v_sent_t), group(hc_t),
            group(att_t), group(rv_t), group(late_t),
        )
    else:
        (honest_t, li_t, li_arg_t, p_t, v_sent_t, v_comm_t, hc_t,
         att_t, rv_t, late_t) = jax.vmap(setup_one)(keys)

        def run_group(p_k, li_k, li_arg_k, v_k, hc_k, att_k, rv_k,
                      late_k):
            att_k, rv_k, late_k = stack_rounds(att_k, rv_k, late_k)
            return mega(
                p_k, li_k, li_arg_k, v_k, hc_k, att_k, rv_k, late_k
            )

        vi_g, dec_g, ovf_g = jax.vmap(run_group)(
            group(p_t), group(li_t), group(li_arg_t), group(v_sent_t),
            group(hc_t), group(att_t), group(rv_t), group(late_t),
        )
    n = keys.shape[0]
    vi_flat = vi_g.reshape((n,) + vi_g.shape[2:])
    dec_flat = dec_g.reshape((n,) + dec_g.shape[2:])
    ovf_flat = ovf_g.reshape((n,))

    def fin(vi_i32, dec, v_comm, honest, overflow):
        decisions = jnp.concatenate([v_comm[None], dec])
        return TrialResult(
            success=success_oracle(decisions, honest[1:]),
            decisions=decisions,
            honest=honest[1:],
            v_comm=v_comm,
            vi=vi_i32 != 0,
            overflow=overflow,
            counters=None,
        )

    return jax.vmap(fin)(
        vi_flat, dec_flat, v_comm_t, honest_t, ovf_flat
    )


def resolve_round_engine(cfg: QBAConfig) -> str:
    """``auto`` -> the fastest engine that compiles for this config.

    Preference order (all gates are cached one-time compile probes
    behind loose VMEM pre-filters): the packet-tiled engine first
    (:func:`qba_tpu.ops.round_kernel_tiled.tiled_kernel_plan`), the
    fused monolithic kernel second
    (:func:`qba_tpu.ops.round_kernel.kernel_compiles`), pure XLA last.

    Round 3 preferred the monolithic kernel below ``size_l < 256``; the
    round-4 tiled-engine work (pool donation, meta packing,
    receiver-major draw tables — docs/PERF.md) flipped every measured
    config to the tiled engine: honest single-batch sweeps show it
    ahead at the headline 11p/64 (28.5k vs 19.3k rounds/s), 21p/64
    (8.6k vs 4.1k), and sizeL 128/256 at both party counts (12-84%).
    The monolithic kernel stays as the second choice (it compiles at
    small scales and keeps shard_map's replication checker usable — see
    parallel/spmd.py)."""
    if cfg.round_engine != "auto":
        return cfg.round_engine
    if jax.default_backend() != "tpu":
        return "xla"
    from qba_tpu.ops.round_kernel import kernel_compiles
    from qba_tpu.ops.round_kernel_tiled import (
        fused_kernel_plan,
        mega_kernel_plan,
        tiled_kernel_plan,
    )

    if tiled_kernel_plan(cfg) is not None:
        # Prefer the fused single-launch kernel where it compiles
        # (docs/PERF.md round 7: one launch per round, no acc/vi HBM
        # round trip); the two-kernel tiled path is its demotion
        # target and the bit-identity reference.
        if fused_kernel_plan(cfg) is not None:
            # ... and the trial megakernel above BOTH where its
            # one-launch plan compiles (docs/PERF.md round 8: the
            # whole round loop in one pallas_call, no per-round
            # launch at all).  Counters need the host round scan, so
            # collect_counters keeps the fused per-round engine.
            if not cfg.collect_counters and (
                mega_kernel_plan(cfg) is not None
            ):
                return "pallas_mega"
            return "pallas_fused"
        return "pallas_tiled"
    if kernel_compiles(cfg):
        return "pallas"
    return "xla"


def run_chunk_counts(
    cfg: QBAConfig, keys: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """One chunk's verdicts reduced ON DEVICE: ``(successes int32,
    overflow bool)`` scalars from a vmapped :func:`run_trial` batch.

    This is the loop body of the device-resident sequential paths
    (``sweep.run_sweep(dispatch="device")`` and the single-dispatch
    adaptive surface): the same per-trial program the host runner
    dispatches (:func:`qba_tpu.backends.jax_backend.batched_trials` is
    the identical ``vmap(run_trial)``), but reduced to the two scalars
    the stopping predicate needs before anything leaves the device —
    so per-chunk counts are bit-identical to the host loop's readback
    for identical keys, and the ``lax.while_loop`` carry stays a few
    words per chunk (the KI-2 carry model,
    analysis/memory.py::device_loop_carry_bytes)."""
    res = jax.vmap(lambda k: run_trial(cfg, k))(keys)
    return (
        jnp.sum(res.success.astype(jnp.int32)),
        jnp.any(res.overflow),
    )


def run_chunk_outcomes(
    cfg: QBAConfig, keys: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Like :func:`run_chunk_counts` but keeps the per-trial success
    bits: ``(success bool[len(keys)], overflow bool)``.  The serve
    device early-finish loop carries these so a device-served result
    reports the same per-trial ``success`` list the host serve path
    assembles from its segment readbacks (docs/SERVING.md)."""
    res = jax.vmap(lambda k: run_trial(cfg, k))(keys)
    return (res.success, jnp.any(res.overflow))


def run_trial(
    cfg: QBAConfig, key: jax.Array, hints: PartitionHints | None = None
) -> TrialResult:
    """One full protocol execution — jit-compilable, vmap-batchable."""
    engine = resolve_round_engine(cfg)
    if engine == "pallas_mega":
        # The megakernel absorbs step 3a and the decision reduce too,
        # so it dispatches before the shared setup below; demotion
        # (counters / no plan) is recorded and lands on pallas_fused.
        if _demote_mega(cfg) is None:
            return run_trial_mega(cfg, key, hints)
        engine = "pallas_fused"
    honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = setup_trial(
        cfg, key, hints
    )

    # Step 3a (tfg.py:185-196), vmapped over lieutenants.
    vi, out_cells = jax.vmap(lambda p, v, li: step3a_one(cfg, p, v, li))(
        p_rows, v_sent, lieu_lists
    )
    mb = Mailbox(*out_cells)

    # Step 3b (tfg.py:337-348): synchronous rounds 1..n_dishonest+1.
    ctx = adversary_ctx(cfg, k_rounds, v_sent)
    if engine == "pallas":
        vi, overflow, counters = run_rounds_pallas(
            cfg, vi, mb, lieu_lists, honest, k_rounds, ctx,
            interpret=jax.default_backend() != "tpu",
        )
    elif engine == "pallas_tiled":
        vi, overflow, counters = run_rounds_tiled(
            cfg, vi, out_cells, lieu_lists, honest, k_rounds, ctx,
            interpret=jax.default_backend() != "tpu",
        )
    elif engine == "pallas_fused":
        vi, overflow, counters = run_rounds_fused(
            cfg, vi, out_cells, lieu_lists, honest, k_rounds, ctx,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        vi, overflow, counters = run_rounds_xla(
            cfg, vi, mb, lieu_lists, honest, k_rounds, ctx
        )
    return finish_trial(cfg, vi, v_comm, honest, overflow, counters)
