"""Synchronous round engine (SURVEY §7.4).

Replaces the reference's MPI voting loop — ``Iprobe`` mailbox drains,
per-field tagged ``Isend``/``Irecv`` packets, and inter-round barriers
(``tfg.py:199-263,335-348``) — with a dense mailbox tensor delivered
deterministically once per round under ``lax.scan``.
"""

from qba_tpu.rounds.mailbox import Mailbox, empty_mailbox
from qba_tpu.rounds.engine import PartitionHints, run_trial, TrialResult

__all__ = ["Mailbox", "empty_mailbox", "PartitionHints", "run_trial", "TrialResult"]
