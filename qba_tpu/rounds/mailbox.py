"""The dense mailbox tensor.

In the reference every accepted packet triggers ``nParties-2`` tagged
point-to-point sends (``lieu_broadcast``, ``tfg.py:266-286``), and each
lieutenant drains its MPI queue with ``Iprobe`` (``tfg.py:337-348``).
Here a round's entire traffic is one fixed-shape pytree: per sending
lieutenant, up to ``slots`` broadcast packets.  Delivery is a gather — every
receiver reads every (sender, slot) cell; per-recipient corruption happens
at read time with per-(sender, slot, receiver) keys, so the sender-side
packet is stored once, not once per recipient.

A cell is addressed ``[sender_lieu_idx, slot]`` where ``sender_lieu_idx =
rank - 2``.  ``sent`` marks occupied cells.  ``slots = w`` is lossless
(docs/DIVERGENCES.md D9); smaller configured bounds record overflow.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL


@struct.dataclass
class Mailbox:
    """All packets broadcast by lieutenants in one round."""

    vals: jnp.ndarray  # int32[senders, slots, max_l, size_l]
    lens: jnp.ndarray  # int32[senders, slots, max_l]
    count: jnp.ndarray  # int32[senders, slots]
    p_mask: jnp.ndarray  # bool[senders, slots, size_l]
    v: jnp.ndarray  # int32[senders, slots]
    sent: jnp.ndarray  # bool[senders, slots]


def empty_mailbox(cfg: QBAConfig) -> Mailbox:
    n_s, slots, max_l, s = cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l
    return Mailbox(
        vals=jnp.full((n_s, slots, max_l, s), SENTINEL, dtype=jnp.int32),
        lens=jnp.zeros((n_s, slots, max_l), dtype=jnp.int32),
        count=jnp.zeros((n_s, slots), dtype=jnp.int32),
        p_mask=jnp.zeros((n_s, slots, s), dtype=bool),
        v=jnp.zeros((n_s, slots), dtype=jnp.int32),
        sent=jnp.zeros((n_s, slots), dtype=bool),
    )
