"""Chunked, checkpoint-resumable Monte-Carlo sweeps.

SURVEY §5 (checkpoint/resume: absent in the reference — runs are one trial
per ``mpiexec`` invocation, state in in-memory Python sets): the TPU
framework's sweeps can run millions of trials, so progress is chunked and
checkpointed — serialize the config fingerprint plus per-chunk aggregates;
resume skips completed chunks and reproduces identical results because each
chunk's key tree is a pure function of ``(seed, chunk_index)``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBACheckpointMismatch, warn_and_record
from qba_tpu.obs.events import EventLog
from qba_tpu.obs.timers import PhaseTimers
from qba_tpu.stats.estimators import SweepEstimators
from qba_tpu.stats.estimators import success_rate as _success_rate
from qba_tpu.stats.sequential import StopDecision
from qba_tpu.stats.targets import Target, parse_target


@dataclasses.dataclass(frozen=True)
class ChunkResult:
    chunk: int
    trials: int
    successes: int
    overflow: bool
    # Per-chunk phase timings (seconds), recorded when the sweep ran with
    # timers; None in checkpoints written before telemetry landed.
    # compare=False: timings are measurement metadata — a resumed sweep's
    # chunks must compare equal to an uninterrupted run's
    # (tests/test_cli_sweep.py pins chunk equality across resume).
    dispatch_s: float | None = dataclasses.field(default=None, compare=False)
    readback_s: float | None = dataclasses.field(default=None, compare=False)


@dataclasses.dataclass(frozen=True)
class SweepResult:
    cfg: QBAConfig
    chunks: tuple[ChunkResult, ...]
    resumed_chunks: int  # how many chunks came from the checkpoint
    # Precision-targeted runs only (run_sweep(target=...)): why the run
    # stopped, with the anytime-valid estimate at stop.  compare=False —
    # the trial data is the identity; a targeted run that executed the
    # same chunks as a fixed-budget run compares equal to it.
    stop: StopDecision | None = dataclasses.field(default=None, compare=False)

    @property
    def n_trials(self) -> int:
        return sum(c.trials for c in self.chunks)

    @property
    def successes(self) -> int:
        return sum(c.successes for c in self.chunks)

    @property
    def success_rate(self) -> float:
        # Single source of truth for the empty case (stats satellite):
        # nan on zero trials, everywhere.
        return _success_rate(self.successes, self.n_trials)

    @property
    def any_overflow(self) -> bool:
        return any(c.overflow for c in self.chunks)

    def estimators(
        self, method: str = "wilson", confidence: float = 0.95
    ) -> SweepEstimators:
        """The certified-rate view of this sweep (docs/STATS.md)."""
        return SweepEstimators(
            method=method, confidence=confidence
        ).observe_all(self.chunks)

    def stats_summary(
        self, method: str = "wilson", confidence: float = 0.95
    ) -> dict[str, Any]:
        """Manifest-ready statistics block: every rate carries a CI, the
        stop decision rides along on targeted runs."""
        out = self.estimators(method=method, confidence=confidence).summary()
        out["n_trials"] = self.n_trials
        if self.stop is not None:
            out["stop"] = self.stop.to_json()
        return out


def chunk_keys(cfg: QBAConfig, chunk: int, chunk_trials: int) -> jax.Array:
    """The chunk's trial keys — pure function of (seed, chunk), so a resumed
    sweep consumes randomness identical to an uninterrupted one."""
    root = jax.random.fold_in(jax.random.key(cfg.seed), chunk)
    return jax.random.split(root, chunk_trials)


def _config_fingerprint(cfg: QBAConfig) -> dict[str, Any]:
    # ``trials`` is chunk sizing, not part of the scientific question —
    # the (forceable) chunk_trials check owns that disagreement, so the
    # CLI's ``--trials`` change doesn't masquerade as a config mismatch.
    d = dataclasses.asdict(cfg)
    d.pop("trials", None)
    return d


def _atomic_write_json(path: str, payload: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(
    path: str, cfg: QBAConfig, chunk_trials: int, force: bool = False
) -> list[ChunkResult]:
    """Completed chunks from ``path``; [] if absent.

    Raises :class:`~qba_tpu.diagnostics.QBACheckpointMismatch` (a
    ``ValueError`` and a ``QBAWarning`` family member, carrying both
    fingerprints) on a config or chunk-size mismatch — a checkpoint is
    only valid for the exact sweep.  ``force=True`` (the CLI's
    ``--resume-force``) downgrades the *chunk_trials* mismatch to a
    warning and returns ``[]`` so the caller re-chunks from scratch
    (the next save overwrites).  A *config* mismatch is never
    forceable: those chunks were drawn from a different program.
    """
    if not os.path.exists(path):
        return []
    with open(path) as f:
        payload = json.load(f)
    # Older checkpoints recorded ``trials`` inside the fingerprint;
    # drop it from the stored side too so they stay resumable.
    stored = dict(payload.get("config") or {})
    stored.pop("trials", None)
    if stored != _config_fingerprint(cfg):
        raise QBACheckpointMismatch(
            f"checkpoint {path} was written for a different config: "
            f"{stored} != {_config_fingerprint(cfg)}",
            kind="config",
            path=path,
            checkpoint_fingerprint=stored,
            requested_fingerprint=_config_fingerprint(cfg),
        )
    if payload.get("chunk_trials") != chunk_trials:
        err = QBACheckpointMismatch(
            f"checkpoint {path} used chunk_trials={payload.get('chunk_trials')}, "
            f"requested {chunk_trials}",
            kind="chunk_trials",
            path=path,
            checkpoint_fingerprint=payload.get("chunk_trials"),
            requested_fingerprint=chunk_trials,
        )
        if not force:
            raise err
        warn_and_record(
            f"{err} — --resume-force: discarding the checkpoint and "
            "re-chunking from scratch",
            QBACheckpointMismatch,
            site="sweep.load_checkpoint",
            path=path,
            checkpoint_chunk_trials=payload.get("chunk_trials"),
            requested_chunk_trials=chunk_trials,
        )
        return []
    return [ChunkResult(**c) for c in payload["chunks"]]


def save_checkpoint(
    path: str,
    cfg: QBAConfig,
    chunk_trials: int,
    chunks: list[ChunkResult],
    stats: dict[str, Any] | None = None,
) -> None:
    payload = {
        "config": _config_fingerprint(cfg),
        "chunk_trials": chunk_trials,
        "chunks": [dataclasses.asdict(c) for c in chunks],
    }
    if stats is not None:
        # Precision-targeted runs persist the target spec + running
        # stop state alongside the chunks; load_checkpoint ignores the
        # block (chunk data alone reconstructs the rule on replay).
        payload["stats"] = stats
    _atomic_write_json(path, payload)


def _default_runner(chunk_trials: int, log: EventLog | None):
    """Single-device vmap batch, or dp-sharded over all devices when
    several are visible and the chunk size divides them."""
    from qba_tpu.backends.jax_backend import batched_trials

    n = len(jax.devices())
    if n == 1 or chunk_trials % n != 0:
        if log and n > 1:
            log.info(
                "sweep",
                "chunk size not divisible by device count; running "
                "single-device",
                devices=n,
                chunk_trials=chunk_trials,
            )
        return batched_trials
    from qba_tpu.parallel import make_mesh, run_trials_sharded

    mesh = make_mesh({"dp": n})
    if log:
        log.info("sweep", "chunks dp-sharded over devices", devices=n)

    def runner(cfg, keys):
        return run_trials_sharded(cfg, mesh, keys).trials

    return runner


def run_chunk(
    cfg: QBAConfig,
    chunk: int,
    chunk_trials: int,
    runner,
    timers: PhaseTimers,
) -> ChunkResult:
    """Execute ONE chunk synchronously: dispatch span, fenced readback
    span, :class:`ChunkResult` out.

    The sequential paths (``target=`` sweeps, the surface allocator)
    use this instead of the double-buffered pipeline: a stopping rule
    must see chunk k's counts before deciding whether chunk k+1 runs at
    all, so overlap would execute work the rule may cancel.  That
    serialization is the documented cost of precision targeting
    (docs/STATS.md); the readback is still fenced so the KI-6 telemetry
    attributes the stall to the device.
    """
    keys = chunk_keys(cfg, chunk, chunk_trials)
    t0 = timers.total("dispatch")
    with timers.time("dispatch", chunk=chunk):
        res = runner(cfg, keys)
    dispatch_s = timers.total("dispatch") - t0
    t1 = timers.total("readback")
    with timers.time("readback", chunk=chunk) as sp:
        successes = int(np.sum(np.asarray(res.success)))
        overflow = bool(np.any(np.asarray(res.overflow)))
        # The np.asarray reads ARE this chunk's host readback barrier.
        sp.fenced = True
    return ChunkResult(
        chunk=chunk,
        trials=chunk_trials,
        successes=successes,
        overflow=overflow,
        dispatch_s=dispatch_s,
        readback_s=timers.total("readback") - t1,
    )


def _replay_prefix(
    loaded: list[ChunkResult], rule, max_chunks: int
) -> tuple[list[ChunkResult], StopDecision | None]:
    """Feed checkpointed chunks to a fresh stopping rule in chunk order.

    Only the contiguous prefix starting at chunk 0 counts: the rule's
    stop point must be a pure function of the canonical chunk order, so
    a resumed targeted run replays exactly the chunks an uninterrupted
    run would have executed, in the same order, and lands in the same
    rule state.  Replay stops at the first decision — trailing
    checkpointed chunks stay in the file but not in the result,
    mirroring where an uninterrupted run would have stopped.
    """
    by_index = {c.chunk: c for c in loaded}
    replayed: list[ChunkResult] = []
    for i in range(max_chunks):
        c = by_index.get(i)
        if c is None:
            break
        rule.observe(c.successes, c.trials)
        replayed.append(c)
        dec = rule.decision()
        if dec is not None:
            return replayed, dec
    return replayed, None


def _run_sweep_targeted(
    cfg: QBAConfig,
    target: Target,
    n_chunks: int,
    chunk_trials: int,
    checkpoint: str | None,
    log: EventLog | None,
    timers: PhaseTimers,
    runner,
    resume_force: bool,
) -> SweepResult:
    """The ``target=`` path of :func:`run_sweep`: chunks run one at a
    time through ``target``'s stopping rule until it fires or the
    ``n_chunks`` budget is exhausted.  Chunk k's keys are the same pure
    function of ``(seed, k)`` as in the fixed-budget path, so the
    executed chunks are bit-identical to a fixed-budget run's prefix —
    the stopping rule only chooses WHERE the prefix ends."""
    rule = target.make_rule()
    loaded = (
        load_checkpoint(checkpoint, cfg, chunk_trials, force=resume_force)
        if checkpoint
        else []
    )
    chunks, decision = _replay_prefix(loaded, rule, n_chunks)
    resumed = len(chunks)
    extra = [c for c in loaded if c.chunk >= len(chunks)]
    if log and resumed:
        log.info(
            "sweep",
            "resumed targeted run from checkpoint",
            chunks=resumed,
            path=checkpoint,
        )

    next_chunk = len(chunks)
    while decision is None and next_chunk < n_chunks:
        if runner is None:
            runner = _default_runner(chunk_trials, log)
        cr = run_chunk(cfg, next_chunk, chunk_trials, runner, timers)
        chunks.append(cr)
        rule.observe(cr.successes, cr.trials)
        decision = rule.decision()
        if checkpoint:
            save_checkpoint(
                checkpoint,
                cfg,
                chunk_trials,
                chunks + extra,
                stats={
                    "target": target.to_json(),
                    "stop": decision.to_json() if decision else None,
                },
            )
        if log:
            log.info(
                "sweep",
                "chunk done",
                chunk=cr.chunk,
                successes=cr.successes,
                trials=cr.trials,
                decided=decision is not None,
            )
        next_chunk += 1

    stop = decision if decision is not None else rule.exhausted()
    if log:
        log.info(
            "sweep",
            "targeted sweep stopped",
            reason=stop.reason,
            n_trials=stop.n_trials,
        )
    return SweepResult(
        cfg=cfg, chunks=tuple(chunks), resumed_chunks=resumed, stop=stop
    )


@dataclasses.dataclass(frozen=True)
class SurfaceCell:
    """One (strategy × noise × size_l) grid point of an adversary
    surface, with the dispatch-decision manifest of the config that
    actually ran (kernel-plan attribution per cell)."""

    strategy: str
    p_depolarize: float
    p_measure_flip: float
    size_l: int
    result: SweepResult
    manifest: dict[str, Any] | None = None


def _surface_grid(
    cfg: QBAConfig,
    strategies,
    noise_points,
    size_ls,
    checkpoint_dir: str | None,
) -> list[tuple[str, float, float, int, QBAConfig, str | None]]:
    """The flattened (strategy × noise × sizeL) cell list with per-cell
    configs and checkpoint paths — shared by both surface paths so the
    uniform and targeted runs agree on cell identity and order."""
    grid = []
    for strat in strategies:
        for p_dep, p_mf in noise_points:
            for size_l in size_ls:
                cfg_cell = dataclasses.replace(
                    cfg,
                    strategy=strat,
                    p_depolarize=p_dep,
                    p_measure_flip=p_mf,
                    size_l=size_l,
                )
                ckpt = None
                if checkpoint_dir:
                    os.makedirs(checkpoint_dir, exist_ok=True)
                    ckpt = os.path.join(
                        checkpoint_dir,
                        f"surface_{strat}_p{p_dep}_q{p_mf}_L{size_l}.json",
                    )
                grid.append((strat, p_dep, p_mf, size_l, cfg_cell, ckpt))
    return grid


def _run_surface_targeted(
    cfg: QBAConfig,
    strategies,
    noise_points,
    size_ls,
    target: Target,
    budget_chunks: int,
    chunk_trials: int,
    checkpoint_dir: str | None,
    log: EventLog | None,
    runner,
    with_manifest: bool,
    resume_force: bool,
) -> list[SurfaceCell]:
    """The ``target=`` path of :func:`run_surface`: one shared chunk
    budget spent across the grid by the adaptive allocator
    (:class:`~qba_tpu.stats.AdaptiveAllocator`) — cells whose CI still
    straddles the decision boundary get chunks first, resolved cells
    stop consuming budget.  Each executed chunk is the same pure
    function of (cell config seed, chunk index) as in the uniform path,
    so per-cell results are bit-identical to a uniform run's prefix;
    only the per-cell chunk *counts* differ."""
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest
    from qba_tpu.stats.allocate import AdaptiveAllocator

    grid = _surface_grid(cfg, strategies, noise_points, size_ls, checkpoint_dir)
    labels = [
        f"{strat}_p{p_dep}_q{p_mf}_L{size_l}"
        for strat, p_dep, p_mf, size_l, _, _ in grid
    ]
    alloc = AdaptiveAllocator(labels, target, budget_chunks)
    timers = PhaseTimers()
    cell_chunks: list[list[ChunkResult]] = [[] for _ in grid]
    cell_decisions: list[list[dict]] = [[] for _ in grid]
    cell_resumed = [0] * len(grid)

    # Resume: replay each cell's checkpointed contiguous prefix through
    # the allocator in cell-index order, chunk order within a cell —
    # the rule state after replay equals the state the interrupted run
    # stopped in (counts are order-exchangeable; docs/STATS.md).
    for idx, (_, _, _, _, cfg_cell, ckpt) in enumerate(grid):
        if not ckpt:
            continue
        loaded = load_checkpoint(ckpt, cfg_cell, chunk_trials, force=resume_force)
        by_index = {c.chunk: c for c in loaded}
        i = 0
        while i in by_index and alloc.cells[idx].decision is None:
            c = by_index[i]
            cell_chunks[idx].append(c)
            alloc.preload(idx, c.successes, c.trials)
            i += 1
        cell_resumed[idx] = len(cell_chunks[idx])
        if log and cell_resumed[idx]:
            log.info(
                "surface",
                "cell resumed from checkpoint",
                cell=labels[idx],
                chunks=cell_resumed[idx],
            )

    while (idx := alloc.next_cell()) is not None:
        strat, p_dep, p_mf, size_l, cfg_cell, ckpt = grid[idx]
        if runner is None:
            runner = _default_runner(chunk_trials, log)
        chunk_index = len(cell_chunks[idx])
        with record_decisions() as decs:
            cr = run_chunk(cfg_cell, chunk_index, chunk_trials, runner, timers)
        cell_decisions[idx].extend(decs)
        cell_chunks[idx].append(cr)
        dec = alloc.record(idx, cr.successes, cr.trials)
        if ckpt:
            save_checkpoint(
                ckpt,
                cfg_cell,
                chunk_trials,
                cell_chunks[idx],
                stats={
                    "target": target.to_json(),
                    "stop": dec.to_json() if dec else None,
                },
            )
        if log:
            log.info(
                "surface",
                "allocated chunk done",
                cell=labels[idx],
                chunk=chunk_index,
                successes=cr.successes,
                decided=dec is not None,
            )

    alloc.finish()
    alloc_summary = alloc.summary()
    decisions = alloc.decisions()
    cells: list[SurfaceCell] = []
    for idx, (strat, p_dep, p_mf, size_l, cfg_cell, _) in enumerate(grid):
        res = SweepResult(
            cfg=cfg_cell,
            chunks=tuple(cell_chunks[idx]),
            resumed_chunks=cell_resumed[idx],
            stop=decisions[idx],
        )
        manifest = None
        if with_manifest:
            stats_block = res.stats_summary(confidence=target.confidence)
            stats_block["target"] = target.to_json()
            stats_block["allocator"] = alloc_summary
            manifest = collect_manifest(
                cfg_cell,
                command="surface",
                decisions=cell_decisions[idx],
                extra={"stats": stats_block},
            )
        cells.append(
            SurfaceCell(
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                result=res,
                manifest=manifest,
            )
        )
        if log:
            log.info(
                "surface",
                "cell resolved",
                cell=labels[idx],
                reason=decisions[idx].reason,
                n_trials=res.n_trials,
            )
    return cells


def run_surface(
    cfg: QBAConfig,
    strategies: tuple[str, ...] | list[str],
    noise_points: list[tuple[float, float]],
    size_ls: list[int],
    n_chunks: int = 1,
    chunk_trials: int | None = None,
    checkpoint_dir: str | None = None,
    log: EventLog | None = None,
    runner=None,
    with_manifest: bool = True,
    target: Target | str | None = None,
    budget_chunks: int | None = None,
    resume_force: bool = False,
) -> list[SurfaceCell]:
    """The (strategy × noise × sizeL) adversary surface as ONE sharded
    Monte-Carlo: every cell is a :func:`run_sweep` over the same runner
    (dp-sharded over all visible devices when several are up — the
    ``parallel.montecarlo`` path), so the whole grid shares key-tree
    discipline, checkpoint format and placement independence.

    ``noise_points`` are ``(p_depolarize, p_measure_flip)`` pairs.  With
    ``checkpoint_dir``, each cell checkpoints to its own file (named by
    the cell coordinates) and a re-run resumes cell-by-cell.  With
    ``with_manifest``, each cell carries the dispatch-decision manifest
    collected around its own run — per-cell kernel attribution, since
    strategy changes the traced round program (forge-P is statically
    gated) and size_l changes the block plan.  Every cell manifest also
    carries a ``stats`` block with the cell's certified success rate
    (point estimate + CI; docs/STATS.md).

    ``target`` switches to the precision-targeted path: the adaptive
    allocator spends one shared chunk budget (``budget_chunks``,
    default ``n_chunks × n_cells`` — the uniform run's total) across
    the grid, largest-uncertainty-first, until every cell's stopping
    rule resolves or the budget runs out.  ``resume_force`` forwards to
    :func:`load_checkpoint`.
    """
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest

    if chunk_trials is None:
        chunk_trials = cfg.trials
    if target is not None:
        if isinstance(target, str):
            target = parse_target(target)
        n_cells = len(strategies) * len(noise_points) * len(size_ls)
        return _run_surface_targeted(
            cfg,
            strategies,
            noise_points,
            size_ls,
            target,
            budget_chunks if budget_chunks is not None else n_chunks * n_cells,
            chunk_trials,
            checkpoint_dir,
            log,
            runner,
            with_manifest,
            resume_force,
        )

    cells: list[SurfaceCell] = []
    grid = _surface_grid(cfg, strategies, noise_points, size_ls, checkpoint_dir)
    for strat, p_dep, p_mf, size_l, cfg_cell, ckpt in grid:
        with record_decisions() as decisions:
            res = run_sweep(
                cfg_cell,
                n_chunks=n_chunks,
                chunk_trials=chunk_trials,
                checkpoint=ckpt,
                log=log,
                runner=runner,
                resume_force=resume_force,
            )
        manifest = (
            collect_manifest(
                cfg_cell,
                command="surface",
                decisions=decisions,
                extra={"stats": res.stats_summary()},
            )
            if with_manifest
            else None
        )
        cells.append(
            SurfaceCell(
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                result=res,
                manifest=manifest,
            )
        )
        if log:
            log.info(
                "surface",
                "cell done",
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                success_rate=res.success_rate,
            )
    return cells


def run_sweep(
    cfg: QBAConfig,
    n_chunks: int,
    chunk_trials: int | None = None,
    checkpoint: str | None = None,
    log: EventLog | None = None,
    timers: PhaseTimers | None = None,
    runner=None,
    target: Target | str | None = None,
    resume_force: bool = False,
) -> SweepResult:
    """Run ``n_chunks`` batches of ``chunk_trials`` trials each.

    ``runner(cfg, keys) -> TrialResult`` defaults to the jitted vmap
    batch on one device, or to trials sharded over a ``dp`` mesh spanning
    all visible devices when there are several (and the chunk size
    divides the device count); the mesh-sharded runners in
    :mod:`qba_tpu.parallel` can also be partial-applied in explicitly.
    With ``checkpoint``, completed chunks are persisted after each chunk
    and skipped on re-run.  Results are placement-independent
    (tests/test_parallel.py), so resuming on different hardware
    reproduces the same sweep.

    ``target`` (a :class:`~qba_tpu.stats.Target` or its string form,
    e.g. ``"decide vs 1/3 @ 95%"`` / ``"ci_width<=0.002"``) switches to
    the precision-targeted path: chunks run one at a time through the
    target's anytime-valid stopping rule and the sweep stops as soon as
    the rule fires — ``n_chunks`` becomes the budget *ceiling*, and
    ``SweepResult.stop`` records the decision.  Executed chunks are
    bit-identical to the fixed-budget run's prefix (docs/STATS.md).
    ``resume_force`` forwards to :func:`load_checkpoint` (re-chunk
    instead of refusing on a chunk_trials mismatch).
    """
    if chunk_trials is None:
        chunk_trials = cfg.trials

    # Opt-in persistent compilation cache: long sweeps re-enter the same
    # per-chunk program across resumes/processes, so a disk-cached
    # executable turns a tens-of-seconds recompile into a file read.
    # Strictly env-gated here — run_sweep is a library entry point, and
    # library code must not silently flip global JAX config (the CLI
    # tool surfaces enable it unconditionally, and the serving
    # subsystem promotes the whole thing to a first-class cache-dir
    # artifact; see :mod:`qba_tpu.compile_cache` and docs/SERVING.md).
    if os.environ.get("QBA_COMPILE_CACHE"):
        from qba_tpu.compile_cache import enable_compile_cache, xla_cache_dir

        enable_compile_cache(xla_cache_dir())

    if target is not None:
        if isinstance(target, str):
            target = parse_target(target)
        return _run_sweep_targeted(
            cfg,
            target,
            n_chunks,
            chunk_trials,
            checkpoint,
            log,
            timers or PhaseTimers(),
            runner,
            resume_force,
        )

    loaded = (
        load_checkpoint(checkpoint, cfg, chunk_trials, force=resume_force)
        if checkpoint
        else []
    )
    # A checkpoint may hold more chunks than this invocation asks for;
    # aggregate only the requested range (the file keeps the full set).
    chunks = [c for c in loaded if c.chunk < n_chunks]
    extra = [c for c in loaded if c.chunk >= n_chunks]
    done = {c.chunk for c in chunks}
    resumed = len(chunks)
    if log and resumed:
        log.info("sweep", "resumed from checkpoint", chunks=resumed, path=checkpoint)

    timers = timers or PhaseTimers()
    todo = [c for c in range(n_chunks) if c not in done]
    # Double-buffered pipeline: dispatch chunk k+1 before fetching chunk
    # k's results, so the host-side readback (expensive on tunneled
    # backends) overlaps the next chunk's device execution.  JAX's async
    # dispatch makes the in-flight window free; depth 2 bounds device
    # memory to two chunk batches.  Dispatch and readback are timed as
    # distinct phases ("dispatch"/"readback") so each phase's count equals
    # the number of chunks and per-chunk means stay honest; a finished
    # chunk is drained-and-checkpointed even if the next dispatch raises.
    in_flight: list[tuple[int, Any, float]] = []

    def drain_one() -> None:
        chunk, res, dispatch_s = in_flight.pop(0)
        t0 = timers.total("readback")
        with timers.time("readback", chunk=chunk) as sp:
            successes = int(np.sum(np.asarray(res.success)))
            overflow = bool(np.any(np.asarray(res.overflow)))
            # The np.asarray reads above ARE the host readback barrier
            # for this chunk's results (docs/PERF.md) — label the span.
            sp.fenced = True
        cr = ChunkResult(
            chunk=chunk,
            trials=chunk_trials,
            successes=successes,
            overflow=overflow,
            dispatch_s=dispatch_s,
            readback_s=timers.total("readback") - t0,
        )
        chunks.append(cr)
        if checkpoint:
            save_checkpoint(checkpoint, cfg, chunk_trials, chunks + extra)
        if log:
            log.info(
                "sweep",
                "chunk done",
                chunk=chunk,
                successes=cr.successes,
                trials=cr.trials,
            )

    try:
        for chunk in todo:
            if runner is None:
                # Lazy: a fully-checkpointed re-run never touches the
                # backend.
                runner = _default_runner(chunk_trials, log)
            keys = chunk_keys(cfg, chunk, chunk_trials)
            t0 = timers.total("dispatch")
            with timers.time("dispatch", chunk=chunk):
                res = runner(cfg, keys)
            in_flight.append((chunk, res, timers.total("dispatch") - t0))
            if len(in_flight) >= 2:
                drain_one()
    finally:
        # Preserve completed work if a dispatch fails mid-pipeline.
        while in_flight:
            drain_one()

    chunks.sort(key=lambda c: c.chunk)
    return SweepResult(cfg=cfg, chunks=tuple(chunks), resumed_chunks=resumed)
