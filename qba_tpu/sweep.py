"""Chunked, checkpoint-resumable Monte-Carlo sweeps.

SURVEY §5 (checkpoint/resume: absent in the reference — runs are one trial
per ``mpiexec`` invocation, state in in-memory Python sets): the TPU
framework's sweeps can run millions of trials, so progress is chunked and
checkpointed — serialize the config fingerprint plus per-chunk aggregates;
resume skips completed chunks and reproduces identical results because each
chunk's key tree is a pure function of ``(seed, chunk_index)``.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import tempfile
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import (
    QBACheckpointMismatch,
    QBAWarning,
    warn_and_record,
)
from qba_tpu.obs.events import EventLog
from qba_tpu.obs.timers import PhaseTimers
from qba_tpu.stats.estimators import SweepEstimators
from qba_tpu.stats.estimators import success_rate as _success_rate
from qba_tpu.stats.sequential import StopDecision
from qba_tpu.stats.targets import Target, parse_target


@dataclasses.dataclass(frozen=True)
class ChunkResult:
    chunk: int
    trials: int
    successes: int
    overflow: bool
    # Per-chunk phase timings (seconds), recorded when the sweep ran with
    # timers; None in checkpoints written before telemetry landed.
    # compare=False: timings are measurement metadata — a resumed sweep's
    # chunks must compare equal to an uninterrupted run's
    # (tests/test_cli_sweep.py pins chunk equality across resume).
    dispatch_s: float | None = dataclasses.field(default=None, compare=False)
    readback_s: float | None = dataclasses.field(default=None, compare=False)


@dataclasses.dataclass(frozen=True)
class SweepResult:
    cfg: QBAConfig
    chunks: tuple[ChunkResult, ...]
    resumed_chunks: int  # how many chunks came from the checkpoint
    # Precision-targeted runs only (run_sweep(target=...)): why the run
    # stopped, with the anytime-valid estimate at stop.  compare=False —
    # the trial data is the identity; a targeted run that executed the
    # same chunks as a fixed-budget run compares equal to it.
    stop: StopDecision | None = dataclasses.field(default=None, compare=False)
    # Which control loop produced the chunks: "host" (per-chunk fenced
    # readbacks) or "device" (one lax.while_loop dispatch, one loop-level
    # fenced readback).  compare=False for the same reason as ``stop`` —
    # both modes execute bit-identical chunks (docs/STATS.md).
    dispatch: str = dataclasses.field(default="host", compare=False)

    @property
    def n_trials(self) -> int:
        return sum(c.trials for c in self.chunks)

    @property
    def successes(self) -> int:
        return sum(c.successes for c in self.chunks)

    @property
    def success_rate(self) -> float:
        # Single source of truth for the empty case (stats satellite):
        # nan on zero trials, everywhere.
        return _success_rate(self.successes, self.n_trials)

    @property
    def any_overflow(self) -> bool:
        return any(c.overflow for c in self.chunks)

    def estimators(
        self, method: str = "wilson", confidence: float = 0.95
    ) -> SweepEstimators:
        """The certified-rate view of this sweep (docs/STATS.md)."""
        return SweepEstimators(
            method=method, confidence=confidence
        ).observe_all(self.chunks)

    def stats_summary(
        self, method: str = "wilson", confidence: float = 0.95
    ) -> dict[str, Any]:
        """Manifest-ready statistics block: every rate carries a CI, the
        stop decision rides along on targeted runs."""
        out = self.estimators(method=method, confidence=confidence).summary()
        out["n_trials"] = self.n_trials
        out["dispatch"] = self.dispatch
        if self.stop is not None:
            out["stop"] = self.stop.to_json()
        return out


def chunk_keys(cfg: QBAConfig, chunk: int, chunk_trials: int) -> jax.Array:
    """The chunk's trial keys — pure function of (seed, chunk), so a resumed
    sweep consumes randomness identical to an uninterrupted one."""
    root = jax.random.fold_in(jax.random.key(cfg.seed), chunk)
    return jax.random.split(root, chunk_trials)


def _config_fingerprint(cfg: QBAConfig) -> dict[str, Any]:
    # ``trials`` is chunk sizing, not part of the scientific question —
    # the (forceable) chunk_trials check owns that disagreement, so the
    # CLI's ``--trials`` change doesn't masquerade as a config mismatch.
    d = dataclasses.asdict(cfg)
    d.pop("trials", None)
    return d


def _atomic_write_json(path: str, payload: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(
    path: str, cfg: QBAConfig, chunk_trials: int, force: bool = False
) -> list[ChunkResult]:
    """Completed chunks from ``path``; [] if absent.

    Raises :class:`~qba_tpu.diagnostics.QBACheckpointMismatch` (a
    ``ValueError`` and a ``QBAWarning`` family member, carrying both
    fingerprints) on a config or chunk-size mismatch — a checkpoint is
    only valid for the exact sweep.  ``force=True`` (the CLI's
    ``--resume-force``) downgrades the *chunk_trials* mismatch to a
    warning and returns ``[]`` so the caller re-chunks from scratch
    (the next save overwrites).  A *config* mismatch is never
    forceable: those chunks were drawn from a different program.
    """
    if not os.path.exists(path):
        return []
    with open(path) as f:
        payload = json.load(f)
    # Older checkpoints recorded ``trials`` inside the fingerprint;
    # drop it from the stored side too so they stay resumable.
    stored = dict(payload.get("config") or {})
    stored.pop("trials", None)
    if stored != _config_fingerprint(cfg):
        raise QBACheckpointMismatch(
            f"checkpoint {path} was written for a different config: "
            f"{stored} != {_config_fingerprint(cfg)}",
            kind="config",
            path=path,
            checkpoint_fingerprint=stored,
            requested_fingerprint=_config_fingerprint(cfg),
        )
    if payload.get("chunk_trials") != chunk_trials:
        err = QBACheckpointMismatch(
            f"checkpoint {path} used chunk_trials={payload.get('chunk_trials')}, "
            f"requested {chunk_trials}",
            kind="chunk_trials",
            path=path,
            checkpoint_fingerprint=payload.get("chunk_trials"),
            requested_fingerprint=chunk_trials,
        )
        if not force:
            raise err
        warn_and_record(
            f"{err} — --resume-force: discarding the checkpoint and "
            "re-chunking from scratch",
            QBACheckpointMismatch,
            site="sweep.load_checkpoint",
            path=path,
            checkpoint_chunk_trials=payload.get("chunk_trials"),
            requested_chunk_trials=chunk_trials,
        )
        return []
    return [ChunkResult(**c) for c in payload["chunks"]]


def save_checkpoint(
    path: str,
    cfg: QBAConfig,
    chunk_trials: int,
    chunks: list[ChunkResult],
    stats: dict[str, Any] | None = None,
) -> None:
    payload = {
        "config": _config_fingerprint(cfg),
        "chunk_trials": chunk_trials,
        "chunks": [dataclasses.asdict(c) for c in chunks],
    }
    if stats is not None:
        # Precision-targeted runs persist the target spec + running
        # stop state alongside the chunks; load_checkpoint ignores the
        # block (chunk data alone reconstructs the rule on replay).
        payload["stats"] = stats
    _atomic_write_json(path, payload)


def _default_runner(chunk_trials: int, log: EventLog | None):
    """Single-device vmap batch, or dp-sharded over all devices when
    several are visible and the chunk size divides them."""
    from qba_tpu.backends.jax_backend import batched_trials

    n = len(jax.devices())
    if n == 1 or chunk_trials % n != 0:
        if log and n > 1:
            log.info(
                "sweep",
                "chunk size not divisible by device count; running "
                "single-device",
                devices=n,
                chunk_trials=chunk_trials,
            )
        return batched_trials
    from qba_tpu.parallel import make_mesh, run_trials_sharded

    mesh = make_mesh({"dp": n})
    if log:
        log.info("sweep", "chunks dp-sharded over devices", devices=n)

    def runner(cfg, keys):
        return run_trials_sharded(cfg, mesh, keys).trials

    return runner


def run_chunk(
    cfg: QBAConfig,
    chunk: int,
    chunk_trials: int,
    runner,
    timers: PhaseTimers,
) -> ChunkResult:
    """Execute ONE chunk synchronously: dispatch span, fenced readback
    span, :class:`ChunkResult` out.

    The sequential paths (``target=`` sweeps, the surface allocator)
    use this instead of the double-buffered pipeline: a stopping rule
    must see chunk k's counts before deciding whether chunk k+1 runs at
    all, so overlap would execute work the rule may cancel.  That
    serialization is the documented cost of precision targeting
    (docs/STATS.md); the readback is still fenced so the KI-6 telemetry
    attributes the stall to the device.
    """
    keys = chunk_keys(cfg, chunk, chunk_trials)
    t0 = timers.total("dispatch")
    with timers.time("dispatch", chunk=chunk):
        res = runner(cfg, keys)
    dispatch_s = timers.total("dispatch") - t0
    t1 = timers.total("readback")
    with timers.time("readback", chunk=chunk) as sp:
        successes = int(np.sum(np.asarray(res.success)))
        overflow = bool(np.any(np.asarray(res.overflow)))
        # The np.asarray reads ARE this chunk's host readback barrier.
        sp.fenced = True
    return ChunkResult(
        chunk=chunk,
        trials=chunk_trials,
        successes=successes,
        overflow=overflow,
        dispatch_s=dispatch_s,
        readback_s=timers.total("readback") - t1,
    )


def _replay_prefix(
    loaded: list[ChunkResult], rule, max_chunks: int
) -> tuple[list[ChunkResult], StopDecision | None]:
    """Feed checkpointed chunks to a fresh stopping rule in chunk order.

    Only the contiguous prefix starting at chunk 0 counts: the rule's
    stop point must be a pure function of the canonical chunk order, so
    a resumed targeted run replays exactly the chunks an uninterrupted
    run would have executed, in the same order, and lands in the same
    rule state.  Replay stops at the first decision — trailing
    checkpointed chunks stay in the file but not in the result,
    mirroring where an uninterrupted run would have stopped.
    """
    by_index = {c.chunk: c for c in loaded}
    replayed: list[ChunkResult] = []
    for i in range(max_chunks):
        c = by_index.get(i)
        if c is None:
            break
        rule.observe(c.successes, c.trials)
        replayed.append(c)
        dec = rule.decision()
        if dec is not None:
            return replayed, dec
    return replayed, None


# ---------------------------------------------------------------------------
# Device-resident sequential decisions (ROADMAP item 3, docs/STATS.md
# "Device-resident stopping"): the stopping predicate IS the condition of
# a lax.while_loop, so a targeted run performs exactly ONE dispatch — no
# per-chunk fenced readback, no host-side rule update in the hot loop.
# The loop carries only integer counts; the typed StopDecision is
# produced on the host by replaying the readback counts through the same
# rule the host loop uses, so the surfaced decision, the executed
# chunks, and the checkpoint payload are identical across dispatch modes.


def _device_while(cfg, n_chunks, chunk_trials, carry, lo, hi, keys_for):
    """The shared while_loop: condition = budget AND NOT stop-table hit.

    Carry is ``(i, k_total, counts[n_chunks], overflow[n_chunks])`` —
    ``i`` counts completed chunks, the tables are indexed by it, and
    per-chunk counts are kept so the host can replay the rule chunk by
    chunk (checkpoint parity across dispatch modes)."""
    from qba_tpu.rounds.engine import run_chunk_counts

    def cond(c):
        i, k_total, _, _ = c
        return (i < n_chunks) & ~((k_total <= lo[i]) | (k_total >= hi[i]))

    def body(c):
        i, k_total, counts, ovf = c
        k, o = run_chunk_counts(cfg, keys_for(i))
        return (i + 1, k_total + k, counts.at[i].set(k), ovf.at[i].set(o))

    return jax.lax.while_loop(cond, body, carry)


@functools.partial(jax.jit, static_argnums=(0, 1, 2), donate_argnums=(3,))
def _device_loop_foldin(cfg, n_chunks, chunk_trials, carry, lo, hi):
    """Device-resident targeted sweep loop with the sweep key
    discipline: chunk ``i``'s keys are re-derived IN the loop body as
    ``split(fold_in(key(seed), i), chunk_trials)`` — exactly
    :func:`chunk_keys` — so the device run consumes randomness
    bit-identical to the host loop's chunk ``i``.  The carry is donated
    (KI-5): the loop state buffers are reused across iterations instead
    of re-allocated per dispatch."""

    def keys_for(i):
        root = jax.random.fold_in(jax.random.key(cfg.seed), i)
        return jax.random.split(root, chunk_trials)

    return _device_while(cfg, n_chunks, chunk_trials, carry, lo, hi, keys_for)


@functools.partial(jax.jit, static_argnums=(0, 1, 2), donate_argnums=(3,))
def _device_loop_prefix(cfg, n_chunks, chunk_trials, carry, lo, hi, keys):
    """Device-resident loop over PRE-ASSIGNED per-trial keys (leading
    axis ``n_chunks * chunk_trials``): chunk ``i`` consumes rows
    ``[i*chunk_trials, (i+1)*chunk_trials)``.  This is the serve key
    discipline (``split(key(seed), trials)`` prefix semantics) — the
    device early-finish path reads the same per-trial keys the host
    serve scheduler would have fed its segments.

    Unlike the sweep loop this carry also keeps the per-trial success
    bits (``succ bool[n_chunks*chunk_trials]``): a served result
    reports the per-trial ``success`` list, not just chunk counts."""
    from qba_tpu.rounds.engine import run_chunk_outcomes

    def cond(c):
        i, k_total, _, _, _ = c
        return (i < n_chunks) & ~((k_total <= lo[i]) | (k_total >= hi[i]))

    def body(c):
        i, k_total, counts, ovf, succ = c
        ks = jax.lax.dynamic_slice_in_dim(
            keys, i * chunk_trials, chunk_trials
        )
        s, o = run_chunk_outcomes(cfg, ks)
        k = jnp.sum(s.astype(jnp.int32))
        succ = jax.lax.dynamic_update_slice_in_dim(
            succ, s, i * chunk_trials, axis=0
        )
        return (i + 1, k_total + k, counts.at[i].set(k), ovf.at[i].set(o), succ)

    return jax.lax.while_loop(cond, body, carry)


def _device_carry(n_chunks: int, start_chunk: int, k_start: int):
    return (
        jnp.int32(start_chunk),
        jnp.int32(k_start),
        jnp.zeros(n_chunks, jnp.int32),
        jnp.zeros(n_chunks, jnp.bool_),
    )


def _device_carry_prefix(n_chunks: int, chunk_trials: int):
    return _device_carry(n_chunks, 0, 0) + (
        jnp.zeros(n_chunks * chunk_trials, jnp.bool_),
    )


def _run_sweep_targeted_device(
    cfg: QBAConfig,
    target: Target,
    n_chunks: int,
    chunk_trials: int,
    checkpoint: str | None,
    log: EventLog | None,
    timers: PhaseTimers,
    resume_force: bool,
) -> SweepResult:
    """The ``dispatch="device"`` targeted path: ONE dispatch of
    :func:`_device_loop_foldin`, one loop-level fenced readback, then a
    host replay of the per-chunk counts through ``target``'s rule —
    yielding the same executed chunks, the same :class:`StopDecision`,
    and the same checkpoint payload as :func:`_run_sweep_targeted` for
    identical keys (tests/test_device_loop.py pins the triad)."""
    from qba_tpu.stats.device import stop_tables

    rule = target.make_rule()
    loaded = (
        load_checkpoint(checkpoint, cfg, chunk_trials, force=resume_force)
        if checkpoint
        else []
    )
    chunks, decision = _replay_prefix(loaded, rule, n_chunks)
    resumed = len(chunks)
    extra = [c for c in loaded if c.chunk >= len(chunks)]
    if log and resumed:
        log.info(
            "sweep",
            "resumed targeted run from checkpoint",
            chunks=resumed,
            path=checkpoint,
            dispatch="device",
        )

    start = len(chunks)
    if decision is None and start < n_chunks:
        lo, hi = stop_tables(target, n_chunks, chunk_trials)
        k_start = sum(c.successes for c in chunks)
        carry = _device_carry(n_chunks, start, k_start)
        with timers.time(
            "device_loop",
            budget_chunks=n_chunks - start,
            chunk_trials=chunk_trials,
        ) as sp:
            i_stop, _, counts, ovf = _device_loop_foldin(
                cfg, n_chunks, chunk_trials, carry,
                jnp.asarray(lo), jnp.asarray(hi),
            )
            # The single loop-level readback barrier: the device decided
            # when to stop; these reads are the only device->host
            # transfer of the whole targeted run.
            i_stop = int(i_stop)
            counts_h = np.asarray(counts)
            ovf_h = np.asarray(ovf)
            sp.fenced = True
        for c in range(start, i_stop):
            cr = ChunkResult(
                chunk=c,
                trials=chunk_trials,
                successes=int(counts_h[c]),
                overflow=bool(ovf_h[c]),
            )
            chunks.append(cr)
            rule.observe(cr.successes, cr.trials)
            decision = rule.decision()
            if decision is not None:
                break
        executed = len(chunks)
        # A decision landing exactly on the final budget chunk is
        # consistent: the loop exits on i == n_chunks either way.
        if executed != i_stop or (decision is None and i_stop < n_chunks):
            # The stop tables are built by bisection over the rule's own
            # arithmetic, so a divergence means a real bug — surface it
            # loudly but keep the (valid) executed chunks.
            warn_and_record(
                "device stop table diverged from the host rule: device "
                f"stopped after {i_stop} chunks, host replay after "
                f"{executed}",
                QBAWarning,
                site="sweep._run_sweep_targeted_device",
                device_stop=i_stop,
                host_stop=executed,
            )
        if checkpoint:
            save_checkpoint(
                checkpoint,
                cfg,
                chunk_trials,
                chunks + extra,
                stats={
                    "target": target.to_json(),
                    "stop": decision.to_json() if decision else None,
                    "dispatch": "device",
                },
            )

    stop = decision if decision is not None else rule.exhausted()
    if log:
        log.info(
            "sweep",
            "targeted sweep stopped",
            reason=stop.reason,
            n_trials=stop.n_trials,
            dispatch="device",
        )
    return SweepResult(
        cfg=cfg,
        chunks=tuple(chunks),
        resumed_chunks=resumed,
        stop=stop,
        dispatch="device",
    )


def _run_sweep_targeted(
    cfg: QBAConfig,
    target: Target,
    n_chunks: int,
    chunk_trials: int,
    checkpoint: str | None,
    log: EventLog | None,
    timers: PhaseTimers,
    runner,
    resume_force: bool,
) -> SweepResult:
    """The ``target=`` path of :func:`run_sweep`: chunks run one at a
    time through ``target``'s stopping rule until it fires or the
    ``n_chunks`` budget is exhausted.  Chunk k's keys are the same pure
    function of ``(seed, k)`` as in the fixed-budget path, so the
    executed chunks are bit-identical to a fixed-budget run's prefix —
    the stopping rule only chooses WHERE the prefix ends."""
    rule = target.make_rule()
    loaded = (
        load_checkpoint(checkpoint, cfg, chunk_trials, force=resume_force)
        if checkpoint
        else []
    )
    chunks, decision = _replay_prefix(loaded, rule, n_chunks)
    resumed = len(chunks)
    extra = [c for c in loaded if c.chunk >= len(chunks)]
    if log and resumed:
        log.info(
            "sweep",
            "resumed targeted run from checkpoint",
            chunks=resumed,
            path=checkpoint,
        )

    next_chunk = len(chunks)
    while decision is None and next_chunk < n_chunks:
        if runner is None:
            runner = _default_runner(chunk_trials, log)
        cr = run_chunk(cfg, next_chunk, chunk_trials, runner, timers)
        chunks.append(cr)
        rule.observe(cr.successes, cr.trials)
        decision = rule.decision()
        if checkpoint:
            save_checkpoint(
                checkpoint,
                cfg,
                chunk_trials,
                chunks + extra,
                stats={
                    "target": target.to_json(),
                    "stop": decision.to_json() if decision else None,
                },
            )
        if log:
            log.info(
                "sweep",
                "chunk done",
                chunk=cr.chunk,
                successes=cr.successes,
                trials=cr.trials,
                decided=decision is not None,
            )
        next_chunk += 1

    stop = decision if decision is not None else rule.exhausted()
    if log:
        log.info(
            "sweep",
            "targeted sweep stopped",
            reason=stop.reason,
            n_trials=stop.n_trials,
        )
    return SweepResult(
        cfg=cfg, chunks=tuple(chunks), resumed_chunks=resumed, stop=stop
    )


@dataclasses.dataclass(frozen=True)
class SurfaceCell:
    """One (strategy × noise × size_l) grid point of an adversary
    surface, with the dispatch-decision manifest of the config that
    actually ran (kernel-plan attribution per cell)."""

    strategy: str
    p_depolarize: float
    p_measure_flip: float
    size_l: int
    result: SweepResult
    manifest: dict[str, Any] | None = None


def _surface_grid(
    cfg: QBAConfig,
    strategies,
    noise_points,
    size_ls,
    checkpoint_dir: str | None,
) -> list[tuple[str, float, float, int, QBAConfig, str | None]]:
    """The flattened (strategy × noise × sizeL) cell list with per-cell
    configs and checkpoint paths — shared by both surface paths so the
    uniform and targeted runs agree on cell identity and order."""
    grid = []
    for strat in strategies:
        for p_dep, p_mf in noise_points:
            for size_l in size_ls:
                cfg_cell = dataclasses.replace(
                    cfg,
                    strategy=strat,
                    p_depolarize=p_dep,
                    p_measure_flip=p_mf,
                    size_l=size_l,
                )
                ckpt = None
                if checkpoint_dir:
                    os.makedirs(checkpoint_dir, exist_ok=True)
                    # Content-addressed cell filename (atlas store
                    # discipline): derived from the config fingerprint
                    # through the hardened injective slug, so cells
                    # produced by independent runs/dirs merge without
                    # renames and distinct configs can never collide.
                    from qba_tpu.atlas.store import cell_slug

                    addressed = os.path.join(
                        checkpoint_dir,
                        cell_slug(_config_fingerprint(cfg_cell)) + ".json",
                    )
                    # Compat shim: an existing pre-atlas layout keeps
                    # resuming from its coordinate-named file until the
                    # addressed one exists (load_checkpoint still
                    # fingerprint-checks it, so a stale coordinate file
                    # for a different config is rejected, not resumed).
                    legacy = os.path.join(
                        checkpoint_dir,
                        f"surface_{strat}_p{p_dep}_q{p_mf}_L{size_l}.json",
                    )
                    ckpt = (
                        legacy
                        if os.path.exists(legacy)
                        and not os.path.exists(addressed)
                        else addressed
                    )
                grid.append((strat, p_dep, p_mf, size_l, cfg_cell, ckpt))
    return grid


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4), donate_argnums=(5,)
)
def _device_surface_loop(
    cfgs, steps, chunk_trials, confidence, threshold, carry, lo, hi
):
    """The single-dispatch adaptive SURFACE: one ``lax.while_loop``
    carrying the allocator's largest-uncertainty-first tiering across
    every grid cell (``cfgs``, a static tuple — one traced branch per
    cell under ``lax.switch``).

    Per step the loop scores every unresolved cell exactly like
    :meth:`AdaptiveAllocator._priority` — tier 0 bootstrap in index
    order, tier 1 straddling / tier 2 undecided widest-CI-first, ties
    by index (``argmin`` returns the first minimum) — then switches
    into the chosen cell's chunk program.  Cell widths come from the
    traced float32 mixture-CI bisection
    (:func:`qba_tpu.stats.device.device_ci_interval`); stop decisions
    always go through the exact integer tables, so float32 can only
    reorder near-tied *scheduling*, never change a cell's decision
    (docs/STATS.md).  ``threshold`` is the decide boundary, or None for
    width targets (every open cell straddles by definition).

    Carry: ``(step, k_cell, i_cell, done, counts[n_cells, budget],
    ovf[n_cells, budget], sched[steps], tier[steps])`` — donated
    (KI-5).  ``sched``/``tier`` record the device's allocation order so
    the host replay reconstructs the exact trace.
    """
    from qba_tpu.rounds.engine import run_chunk_counts
    from qba_tpu.stats.device import device_ci_interval

    n_cells = len(cfgs)
    branches = [
        (lambda keys, c=c: run_chunk_counts(c, keys)) for c in cfgs
    ]
    seed = cfgs[0].seed  # chunk keys are seed+index pure; seed is shared

    def cond(c):
        s, _, _, done, _, _, _, _ = c
        return (s < steps) & ~jnp.all(done)

    def body(c):
        s, kc, ic, done, counts, ovf, sched, tier_log = c
        ci_lo, ci_hi = jax.vmap(
            lambda k, n: device_ci_interval(k, n, confidence)
        )(kc, ic * chunk_trials)
        width = ci_hi - ci_lo
        boot = ic == 0
        if threshold is None:
            straddle = jnp.ones(n_cells, bool)
        else:
            straddle = (ci_lo <= threshold) & (threshold <= ci_hi)
        tier = jnp.where(boot, 0, jnp.where(straddle, 1, 2))
        # Lexicographic (tier, -width, index) as one float score: tiers
        # are 2 apart, 1-width is in [0, 1], bootstrap ignores width
        # (host sorts bootstrap cells purely by index); argmin takes
        # the first minimum, which IS the index tie-break.
        score = jnp.where(
            done,
            jnp.float32(1e9),
            tier.astype(jnp.float32) * 2.0
            + jnp.where(boot, 0.0, 1.0 - width),
        )
        chosen = jnp.argmin(score)
        i_cur = ic[chosen]
        root = jax.random.fold_in(jax.random.key(seed), i_cur)
        keys = jax.random.split(root, chunk_trials)
        k, o = jax.lax.switch(chosen, branches, keys)
        k_new = kc[chosen] + k
        i_new = i_cur + 1
        stopped = (k_new <= lo[i_new]) | (k_new >= hi[i_new])
        return (
            s + 1,
            kc.at[chosen].set(k_new),
            ic.at[chosen].set(i_new),
            done.at[chosen].set(stopped),
            counts.at[chosen, i_cur].set(k),
            ovf.at[chosen, i_cur].set(o),
            sched.at[s].set(chosen),
            tier_log.at[s].set(tier[chosen]),
        )

    return jax.lax.while_loop(cond, body, carry)


def _run_surface_targeted_device(
    cfg: QBAConfig,
    strategies,
    noise_points,
    size_ls,
    target: Target,
    budget_chunks: int,
    chunk_trials: int,
    checkpoint_dir: str | None,
    log: EventLog | None,
    with_manifest: bool,
    resume_force: bool,
) -> list[SurfaceCell]:
    """The ``dispatch="device"`` surface: the whole adaptive grid runs
    as ONE dispatch of :func:`_device_surface_loop`; the host replays
    the readback (schedule order + per-cell counts) through the same
    per-cell rules to surface typed :class:`StopDecision`\\ s, the
    allocator trace, per-cell checkpoints and manifests — identical
    artifact shapes to :func:`_run_surface_targeted`."""
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest
    from qba_tpu.stats.device import stop_tables

    grid = _surface_grid(cfg, strategies, noise_points, size_ls, checkpoint_dir)
    labels = [
        f"{strat}_p{p_dep}_q{p_mf}_L{size_l}"
        for strat, p_dep, p_mf, size_l, _, _ in grid
    ]
    n_cells = len(grid)
    timers = PhaseTimers()
    rules = [target.make_rule() for _ in grid]
    cell_chunks: list[list[ChunkResult]] = [[] for _ in grid]
    cell_decision: list[StopDecision | None] = [None] * n_cells
    cell_resumed = [0] * n_cells
    trace: list[dict[str, Any]] = []

    # Resume: replay each cell's checkpointed contiguous prefix, in
    # cell-index order — same rule state and budget accounting as the
    # host allocator's preload.
    spent = 0
    for idx, (_, _, _, _, cfg_cell, ckpt) in enumerate(grid):
        if not ckpt:
            continue
        loaded = load_checkpoint(
            ckpt, cfg_cell, chunk_trials, force=resume_force
        )
        replayed, dec = _replay_prefix(loaded, rules[idx], budget_chunks)
        cell_chunks[idx] = replayed
        cell_decision[idx] = dec
        cell_resumed[idx] = len(replayed)
        for _ in replayed:
            trace.append(
                {
                    "step": spent,
                    "cell": idx,
                    "label": labels[idx],
                    "reason": "resume",
                    "ci_width": None,
                }
            )
            spent += 1
        if log and cell_resumed[idx]:
            log.info(
                "surface",
                "cell resumed from checkpoint",
                cell=labels[idx],
                chunks=cell_resumed[idx],
            )

    steps = max(0, budget_chunks - spent)
    open_cells = any(d is None for d in cell_decision)
    decisions_log: list[dict] = []
    if steps > 0 and open_cells:
        lo, hi = stop_tables(target, budget_chunks, chunk_trials)
        carry = (
            jnp.int32(0),
            jnp.asarray([r.k for r in rules], jnp.int32),
            jnp.asarray([len(c) for c in cell_chunks], jnp.int32),
            jnp.asarray([d is not None for d in cell_decision], bool),
            jnp.zeros((n_cells, budget_chunks), jnp.int32),
            jnp.zeros((n_cells, budget_chunks), jnp.bool_),
            jnp.zeros(steps, jnp.int32),
            jnp.zeros(steps, jnp.int32),
        )
        cfgs = tuple(g[4] for g in grid)
        threshold = target.threshold if target.kind == "decide" else None
        with record_decisions() as decisions_log:
            with timers.time(
                "device_loop",
                budget_chunks=steps,
                cells=n_cells,
                chunk_trials=chunk_trials,
            ) as sp:
                out = _device_surface_loop(
                    cfgs, steps, chunk_trials,
                    target.confidence, threshold, carry,
                    jnp.asarray(lo), jnp.asarray(hi),
                )
                s_final = int(out[0])
                counts_h = np.asarray(out[4])
                ovf_h = np.asarray(out[5])
                sched_h = np.asarray(out[6])
                tier_h = np.asarray(out[7])
                sp.fenced = True

        # Host replay of the device schedule: exact rule state, exact
        # decisions, manifest-grade trace.
        for s in range(s_final):
            idx = int(sched_h[s])
            chunk_index = len(cell_chunks[idx])
            est_width = (
                rules[idx].estimate().width if chunk_index else None
            )
            cr = ChunkResult(
                chunk=chunk_index,
                trials=chunk_trials,
                successes=int(counts_h[idx, chunk_index]),
                overflow=bool(ovf_h[idx, chunk_index]),
            )
            cell_chunks[idx].append(cr)
            rules[idx].observe(cr.successes, cr.trials)
            trace.append(
                {
                    "step": spent,
                    "cell": idx,
                    "label": labels[idx],
                    "reason": (
                        "bootstrap", "straddling", "undecided"
                    )[int(tier_h[s])],
                    "ci_width": est_width,
                }
            )
            spent += 1
            dec = rules[idx].decision()
            if dec is not None and cell_decision[idx] is None:
                cell_decision[idx] = dec
            if log:
                log.info(
                    "surface",
                    "allocated chunk done",
                    cell=labels[idx],
                    chunk=chunk_index,
                    successes=cr.successes,
                    decided=dec is not None,
                    dispatch="device",
                )

    for idx, (_, _, _, _, cfg_cell, ckpt) in enumerate(grid):
        if ckpt and len(cell_chunks[idx]) > cell_resumed[idx]:
            save_checkpoint(
                ckpt,
                cfg_cell,
                chunk_trials,
                cell_chunks[idx],
                stats={
                    "target": target.to_json(),
                    "stop": (
                        cell_decision[idx].to_json()
                        if cell_decision[idx]
                        else None
                    ),
                    "dispatch": "device",
                },
            )

    decisions = [
        cell_decision[i]
        if cell_decision[i] is not None
        else rules[i].exhausted()
        for i in range(n_cells)
    ]
    alloc_summary = {
        "target": target.to_json(),
        "budget_chunks": budget_chunks,
        "spent_chunks": spent,
        "dispatch": "device",
        "cells": [
            {
                "index": i,
                "label": labels[i],
                "chunks_run": len(cell_chunks[i]),
                "decision": decisions[i].to_json(),
            }
            for i in range(n_cells)
        ],
        "trace": trace,
    }
    cells: list[SurfaceCell] = []
    for idx, (strat, p_dep, p_mf, size_l, cfg_cell, _) in enumerate(grid):
        res = SweepResult(
            cfg=cfg_cell,
            chunks=tuple(cell_chunks[idx]),
            resumed_chunks=cell_resumed[idx],
            stop=decisions[idx],
            dispatch="device",
        )
        manifest = None
        if with_manifest:
            stats_block = res.stats_summary(confidence=target.confidence)
            stats_block["target"] = target.to_json()
            stats_block["allocator"] = alloc_summary
            manifest = collect_manifest(
                cfg_cell,
                command="surface",
                decisions=list(decisions_log),
                extra={"stats": stats_block},
            )
        cells.append(
            SurfaceCell(
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                result=res,
                manifest=manifest,
            )
        )
        if log:
            log.info(
                "surface",
                "cell resolved",
                cell=labels[idx],
                reason=decisions[idx].reason,
                n_trials=res.n_trials,
            )
    return cells


def _run_surface_targeted(
    cfg: QBAConfig,
    strategies,
    noise_points,
    size_ls,
    target: Target,
    budget_chunks: int,
    chunk_trials: int,
    checkpoint_dir: str | None,
    log: EventLog | None,
    runner,
    with_manifest: bool,
    resume_force: bool,
) -> list[SurfaceCell]:
    """The ``target=`` path of :func:`run_surface`: one shared chunk
    budget spent across the grid by the adaptive allocator
    (:class:`~qba_tpu.stats.AdaptiveAllocator`) — cells whose CI still
    straddles the decision boundary get chunks first, resolved cells
    stop consuming budget.  Each executed chunk is the same pure
    function of (cell config seed, chunk index) as in the uniform path,
    so per-cell results are bit-identical to a uniform run's prefix;
    only the per-cell chunk *counts* differ."""
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest
    from qba_tpu.stats.allocate import AdaptiveAllocator

    grid = _surface_grid(cfg, strategies, noise_points, size_ls, checkpoint_dir)
    labels = [
        f"{strat}_p{p_dep}_q{p_mf}_L{size_l}"
        for strat, p_dep, p_mf, size_l, _, _ in grid
    ]
    alloc = AdaptiveAllocator(labels, target, budget_chunks)
    timers = PhaseTimers()
    cell_chunks: list[list[ChunkResult]] = [[] for _ in grid]
    cell_decisions: list[list[dict]] = [[] for _ in grid]
    cell_resumed = [0] * len(grid)

    # Resume: replay each cell's checkpointed contiguous prefix through
    # the allocator in cell-index order, chunk order within a cell —
    # the rule state after replay equals the state the interrupted run
    # stopped in (counts are order-exchangeable; docs/STATS.md).
    for idx, (_, _, _, _, cfg_cell, ckpt) in enumerate(grid):
        if not ckpt:
            continue
        loaded = load_checkpoint(ckpt, cfg_cell, chunk_trials, force=resume_force)
        by_index = {c.chunk: c for c in loaded}
        i = 0
        while i in by_index and alloc.cells[idx].decision is None:
            c = by_index[i]
            cell_chunks[idx].append(c)
            alloc.preload(idx, c.successes, c.trials)
            i += 1
        cell_resumed[idx] = len(cell_chunks[idx])
        if log and cell_resumed[idx]:
            log.info(
                "surface",
                "cell resumed from checkpoint",
                cell=labels[idx],
                chunks=cell_resumed[idx],
            )

    while (idx := alloc.next_cell()) is not None:
        strat, p_dep, p_mf, size_l, cfg_cell, ckpt = grid[idx]
        if runner is None:
            runner = _default_runner(chunk_trials, log)
        chunk_index = len(cell_chunks[idx])
        with record_decisions() as decs:
            cr = run_chunk(cfg_cell, chunk_index, chunk_trials, runner, timers)
        cell_decisions[idx].extend(decs)
        cell_chunks[idx].append(cr)
        dec = alloc.record(idx, cr.successes, cr.trials)
        if ckpt:
            save_checkpoint(
                ckpt,
                cfg_cell,
                chunk_trials,
                cell_chunks[idx],
                stats={
                    "target": target.to_json(),
                    "stop": dec.to_json() if dec else None,
                },
            )
        if log:
            log.info(
                "surface",
                "allocated chunk done",
                cell=labels[idx],
                chunk=chunk_index,
                successes=cr.successes,
                decided=dec is not None,
            )

    alloc.finish()
    alloc_summary = alloc.summary()
    decisions = alloc.decisions()
    cells: list[SurfaceCell] = []
    for idx, (strat, p_dep, p_mf, size_l, cfg_cell, _) in enumerate(grid):
        res = SweepResult(
            cfg=cfg_cell,
            chunks=tuple(cell_chunks[idx]),
            resumed_chunks=cell_resumed[idx],
            stop=decisions[idx],
        )
        manifest = None
        if with_manifest:
            stats_block = res.stats_summary(confidence=target.confidence)
            stats_block["target"] = target.to_json()
            stats_block["allocator"] = alloc_summary
            manifest = collect_manifest(
                cfg_cell,
                command="surface",
                decisions=cell_decisions[idx],
                extra={"stats": stats_block},
            )
        cells.append(
            SurfaceCell(
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                result=res,
                manifest=manifest,
            )
        )
        if log:
            log.info(
                "surface",
                "cell resolved",
                cell=labels[idx],
                reason=decisions[idx].reason,
                n_trials=res.n_trials,
            )
    return cells


def run_surface(
    cfg: QBAConfig,
    strategies: tuple[str, ...] | list[str],
    noise_points: list[tuple[float, float]],
    size_ls: list[int],
    n_chunks: int = 1,
    chunk_trials: int | None = None,
    checkpoint_dir: str | None = None,
    log: EventLog | None = None,
    runner=None,
    with_manifest: bool = True,
    target: Target | str | None = None,
    budget_chunks: int | None = None,
    resume_force: bool = False,
    dispatch: str = "host",
    store_dir: str | None = None,
) -> list[SurfaceCell]:
    """The (strategy × noise × sizeL) adversary surface as ONE sharded
    Monte-Carlo: every cell is a :func:`run_sweep` over the same runner
    (dp-sharded over all visible devices when several are up — the
    ``parallel.montecarlo`` path), so the whole grid shares key-tree
    discipline, checkpoint format and placement independence.

    ``noise_points`` are ``(p_depolarize, p_measure_flip)`` pairs.  With
    ``checkpoint_dir``, each cell checkpoints to its own file (named by
    the cell coordinates) and a re-run resumes cell-by-cell.  With
    ``with_manifest``, each cell carries the dispatch-decision manifest
    collected around its own run — per-cell kernel attribution, since
    strategy changes the traced round program (forge-P is statically
    gated) and size_l changes the block plan.  Every cell manifest also
    carries a ``stats`` block with the cell's certified success rate
    (point estimate + CI; docs/STATS.md).

    ``target`` switches to the precision-targeted path: the adaptive
    allocator spends one shared chunk budget (``budget_chunks``,
    default ``n_chunks × n_cells`` — the uniform run's total) across
    the grid, largest-uncertainty-first, until every cell's stopping
    rule resolves or the budget runs out.  ``resume_force`` forwards to
    :func:`load_checkpoint`.

    ``dispatch="device"`` (targeted runs only) moves the allocator loop
    itself onto the device: the whole grid becomes ONE
    ``lax.while_loop`` dispatch carrying the uncertainty tiering across
    cells (docs/STATS.md "Device-resident stopping").  Per-cell chunk
    contents and stop decisions match the host allocator's rules
    exactly; the *schedule* may reorder near-tied cells (float32 width
    ordering on device vs float64 on host).

    ``store_dir`` additionally publishes every finished cell into a
    content-addressed atlas store (:mod:`qba_tpu.atlas.store`) —
    targeted cells land certified (or refused on budget exhaustion),
    fixed-budget cells land as uncertified estimates; independently
    produced surfaces merge into one store because the filenames are
    config-fingerprint hashes, not coordinates.
    """
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest

    if dispatch not in ("host", "device"):
        raise ValueError(
            f"dispatch must be 'host' or 'device', got {dispatch!r}"
        )
    if dispatch == "device" and target is None:
        raise ValueError(
            "dispatch='device' needs a target: the device surface loop's "
            "condition is the all-cells-resolved predicate"
        )
    if dispatch == "device" and runner is not None:
        raise ValueError(
            "dispatch='device' cannot take a custom runner: the loop "
            "body switches into each cell's traced chunk program"
        )
    if chunk_trials is None:
        chunk_trials = cfg.trials
    if target is not None:
        if isinstance(target, str):
            target = parse_target(target)
        n_cells = len(strategies) * len(noise_points) * len(size_ls)
        if dispatch == "device":
            cells = _run_surface_targeted_device(
                cfg,
                strategies,
                noise_points,
                size_ls,
                target,
                budget_chunks
                if budget_chunks is not None
                else n_chunks * n_cells,
                chunk_trials,
                checkpoint_dir,
                log,
                with_manifest,
                resume_force,
            )
        else:
            cells = _run_surface_targeted(
                cfg,
                strategies,
                noise_points,
                size_ls,
                target,
                budget_chunks
                if budget_chunks is not None
                else n_chunks * n_cells,
                chunk_trials,
                checkpoint_dir,
                log,
                runner,
                with_manifest,
                resume_force,
            )
        return _publish_surface_cells(cells, store_dir, target, chunk_trials)

    cells: list[SurfaceCell] = []
    grid = _surface_grid(cfg, strategies, noise_points, size_ls, checkpoint_dir)
    for strat, p_dep, p_mf, size_l, cfg_cell, ckpt in grid:
        with record_decisions() as decisions:
            res = run_sweep(
                cfg_cell,
                n_chunks=n_chunks,
                chunk_trials=chunk_trials,
                checkpoint=ckpt,
                log=log,
                runner=runner,
                resume_force=resume_force,
            )
        manifest = (
            collect_manifest(
                cfg_cell,
                command="surface",
                decisions=decisions,
                extra={"stats": res.stats_summary()},
            )
            if with_manifest
            else None
        )
        cells.append(
            SurfaceCell(
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                result=res,
                manifest=manifest,
            )
        )
        if log:
            log.info(
                "surface",
                "cell done",
                strategy=strat,
                p_depolarize=p_dep,
                p_measure_flip=p_mf,
                size_l=size_l,
                success_rate=res.success_rate,
            )
    return _publish_surface_cells(cells, store_dir, None, chunk_trials)


def _publish_surface_cells(
    cells: list[SurfaceCell],
    store_dir: str | None,
    target: Target | None,
    chunk_trials: int,
) -> list[SurfaceCell]:
    """Optionally publish surface cells into a content-addressed atlas
    store (``run_surface(store_dir=...)``); always returns the cells."""
    if store_dir:
        from qba_tpu.atlas.store import AtlasStore, record_from_surface_cell

        store = AtlasStore(store_dir)
        for cell in cells:
            store.write_cell(
                record_from_surface_cell(cell, target, chunk_trials)
            )
    return cells


def run_sweep(
    cfg: QBAConfig,
    n_chunks: int,
    chunk_trials: int | None = None,
    checkpoint: str | None = None,
    log: EventLog | None = None,
    timers: PhaseTimers | None = None,
    runner=None,
    target: Target | str | None = None,
    resume_force: bool = False,
    dispatch: str = "host",
) -> SweepResult:
    """Run ``n_chunks`` batches of ``chunk_trials`` trials each.

    ``runner(cfg, keys) -> TrialResult`` defaults to the jitted vmap
    batch on one device, or to trials sharded over a ``dp`` mesh spanning
    all visible devices when there are several (and the chunk size
    divides the device count); the mesh-sharded runners in
    :mod:`qba_tpu.parallel` can also be partial-applied in explicitly.
    With ``checkpoint``, completed chunks are persisted after each chunk
    and skipped on re-run.  Results are placement-independent
    (tests/test_parallel.py), so resuming on different hardware
    reproduces the same sweep.

    ``target`` (a :class:`~qba_tpu.stats.Target` or its string form,
    e.g. ``"decide vs 1/3 @ 95%"`` / ``"ci_width<=0.002"``) switches to
    the precision-targeted path: chunks run one at a time through the
    target's anytime-valid stopping rule and the sweep stops as soon as
    the rule fires — ``n_chunks`` becomes the budget *ceiling*, and
    ``SweepResult.stop`` records the decision.  Executed chunks are
    bit-identical to the fixed-budget run's prefix (docs/STATS.md).
    ``resume_force`` forwards to :func:`load_checkpoint` (re-chunk
    instead of refusing on a chunk_trials mismatch).

    ``dispatch`` selects the targeted run's control loop: ``"host"``
    (the PR 10 per-chunk loop — dispatch, fenced readback, host rule
    update, repeat) or ``"device"`` (the whole budget in ONE
    ``lax.while_loop`` whose condition is the stopping predicate; one
    loop-level fenced readback).  Both execute bit-identical chunks and
    stop at the same chunk boundary (docs/STATS.md "Device-resident
    stopping").  ``"device"`` requires ``target`` and runs the built-in
    engine batch — it cannot take a custom ``runner`` (the loop body is
    the traced program itself).
    """
    if dispatch not in ("host", "device"):
        raise ValueError(
            f"dispatch must be 'host' or 'device', got {dispatch!r}"
        )
    if dispatch == "device":
        if target is None:
            raise ValueError(
                "dispatch='device' needs a target: the device loop's "
                "condition IS the stopping predicate (a fixed-budget "
                "sweep has nothing to decide on device — use the "
                "double-buffered host path)"
            )
        if runner is not None:
            raise ValueError(
                "dispatch='device' cannot take a custom runner: the "
                "loop body is the traced vmap(run_trial) chunk program"
            )
    if chunk_trials is None:
        chunk_trials = cfg.trials

    # Opt-in persistent compilation cache: long sweeps re-enter the same
    # per-chunk program across resumes/processes, so a disk-cached
    # executable turns a tens-of-seconds recompile into a file read.
    # Strictly env-gated here — run_sweep is a library entry point, and
    # library code must not silently flip global JAX config (the CLI
    # tool surfaces enable it unconditionally, and the serving
    # subsystem promotes the whole thing to a first-class cache-dir
    # artifact; see :mod:`qba_tpu.compile_cache` and docs/SERVING.md).
    if os.environ.get("QBA_COMPILE_CACHE"):
        from qba_tpu.compile_cache import enable_compile_cache, xla_cache_dir

        enable_compile_cache(xla_cache_dir())

    if target is not None:
        if isinstance(target, str):
            target = parse_target(target)
        if dispatch == "device":
            return _run_sweep_targeted_device(
                cfg,
                target,
                n_chunks,
                chunk_trials,
                checkpoint,
                log,
                timers or PhaseTimers(),
                resume_force,
            )
        return _run_sweep_targeted(
            cfg,
            target,
            n_chunks,
            chunk_trials,
            checkpoint,
            log,
            timers or PhaseTimers(),
            runner,
            resume_force,
        )

    loaded = (
        load_checkpoint(checkpoint, cfg, chunk_trials, force=resume_force)
        if checkpoint
        else []
    )
    # A checkpoint may hold more chunks than this invocation asks for;
    # aggregate only the requested range (the file keeps the full set).
    chunks = [c for c in loaded if c.chunk < n_chunks]
    extra = [c for c in loaded if c.chunk >= n_chunks]
    done = {c.chunk for c in chunks}
    resumed = len(chunks)
    if log and resumed:
        log.info("sweep", "resumed from checkpoint", chunks=resumed, path=checkpoint)

    timers = timers or PhaseTimers()
    todo = [c for c in range(n_chunks) if c not in done]
    # Double-buffered pipeline: dispatch chunk k+1 before fetching chunk
    # k's results, so the host-side readback (expensive on tunneled
    # backends) overlaps the next chunk's device execution.  JAX's async
    # dispatch makes the in-flight window free; depth 2 bounds device
    # memory to two chunk batches.  Dispatch and readback are timed as
    # distinct phases ("dispatch"/"readback") so each phase's count equals
    # the number of chunks and per-chunk means stay honest; a finished
    # chunk is drained-and-checkpointed even if the next dispatch raises.
    in_flight: list[tuple[int, Any, float]] = []

    def drain_one() -> None:
        chunk, res, dispatch_s = in_flight.pop(0)
        t0 = timers.total("readback")
        with timers.time("readback", chunk=chunk) as sp:
            successes = int(np.sum(np.asarray(res.success)))
            overflow = bool(np.any(np.asarray(res.overflow)))
            # The np.asarray reads above ARE the host readback barrier
            # for this chunk's results (docs/PERF.md) — label the span.
            sp.fenced = True
        cr = ChunkResult(
            chunk=chunk,
            trials=chunk_trials,
            successes=successes,
            overflow=overflow,
            dispatch_s=dispatch_s,
            readback_s=timers.total("readback") - t0,
        )
        chunks.append(cr)
        if checkpoint:
            save_checkpoint(checkpoint, cfg, chunk_trials, chunks + extra)
        if log:
            log.info(
                "sweep",
                "chunk done",
                chunk=chunk,
                successes=cr.successes,
                trials=cr.trials,
            )

    try:
        for chunk in todo:
            if runner is None:
                # Lazy: a fully-checkpointed re-run never touches the
                # backend.
                runner = _default_runner(chunk_trials, log)
            keys = chunk_keys(cfg, chunk, chunk_trials)
            t0 = timers.total("dispatch")
            with timers.time("dispatch", chunk=chunk):
                res = runner(cfg, keys)
            in_flight.append((chunk, res, timers.total("dispatch") - t0))
            if len(in_flight) >= 2:
                drain_one()
    finally:
        # Preserve completed work if a dispatch fails mid-pipeline.
        while in_flight:
            drain_one()

    chunks.sort(key=lambda c: c.chunk)
    return SweepResult(cfg=cfg, chunks=tuple(chunks), resumed_chunks=resumed)
