"""Chunked, checkpoint-resumable Monte-Carlo sweeps.

SURVEY §5 (checkpoint/resume: absent in the reference — runs are one trial
per ``mpiexec`` invocation, state in in-memory Python sets): the TPU
framework's sweeps can run millions of trials, so progress is chunked and
checkpointed — serialize the config fingerprint plus per-chunk aggregates;
resume skips completed chunks and reproduces identical results because each
chunk's key tree is a pure function of ``(seed, chunk_index)``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.obs.events import EventLog
from qba_tpu.obs.timers import PhaseTimers


@dataclasses.dataclass(frozen=True)
class ChunkResult:
    chunk: int
    trials: int
    successes: int
    overflow: bool
    # Per-chunk phase timings (seconds), recorded when the sweep ran with
    # timers; None in checkpoints written before telemetry landed.
    # compare=False: timings are measurement metadata — a resumed sweep's
    # chunks must compare equal to an uninterrupted run's
    # (tests/test_cli_sweep.py pins chunk equality across resume).
    dispatch_s: float | None = dataclasses.field(default=None, compare=False)
    readback_s: float | None = dataclasses.field(default=None, compare=False)


@dataclasses.dataclass(frozen=True)
class SweepResult:
    cfg: QBAConfig
    chunks: tuple[ChunkResult, ...]
    resumed_chunks: int  # how many chunks came from the checkpoint

    @property
    def n_trials(self) -> int:
        return sum(c.trials for c in self.chunks)

    @property
    def successes(self) -> int:
        return sum(c.successes for c in self.chunks)

    @property
    def success_rate(self) -> float:
        return self.successes / self.n_trials if self.n_trials else float("nan")

    @property
    def any_overflow(self) -> bool:
        return any(c.overflow for c in self.chunks)


def chunk_keys(cfg: QBAConfig, chunk: int, chunk_trials: int) -> jax.Array:
    """The chunk's trial keys — pure function of (seed, chunk), so a resumed
    sweep consumes randomness identical to an uninterrupted one."""
    root = jax.random.fold_in(jax.random.key(cfg.seed), chunk)
    return jax.random.split(root, chunk_trials)


def _config_fingerprint(cfg: QBAConfig) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def _atomic_write_json(path: str, payload: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str, cfg: QBAConfig, chunk_trials: int) -> list[ChunkResult]:
    """Completed chunks from ``path``; [] if absent.  Raises on a config or
    chunk-size mismatch (a checkpoint is only valid for the exact sweep)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        payload = json.load(f)
    if payload.get("config") != _config_fingerprint(cfg):
        raise ValueError(
            f"checkpoint {path} was written for a different config: "
            f"{payload.get('config')} != {_config_fingerprint(cfg)}"
        )
    if payload.get("chunk_trials") != chunk_trials:
        raise ValueError(
            f"checkpoint {path} used chunk_trials={payload.get('chunk_trials')}, "
            f"requested {chunk_trials}"
        )
    return [ChunkResult(**c) for c in payload["chunks"]]


def save_checkpoint(
    path: str, cfg: QBAConfig, chunk_trials: int, chunks: list[ChunkResult]
) -> None:
    _atomic_write_json(
        path,
        {
            "config": _config_fingerprint(cfg),
            "chunk_trials": chunk_trials,
            "chunks": [dataclasses.asdict(c) for c in chunks],
        },
    )


def _default_runner(chunk_trials: int, log: EventLog | None):
    """Single-device vmap batch, or dp-sharded over all devices when
    several are visible and the chunk size divides them."""
    from qba_tpu.backends.jax_backend import batched_trials

    n = len(jax.devices())
    if n == 1 or chunk_trials % n != 0:
        if log and n > 1:
            log.info(
                "sweep",
                "chunk size not divisible by device count; running "
                "single-device",
                devices=n,
                chunk_trials=chunk_trials,
            )
        return batched_trials
    from qba_tpu.parallel import make_mesh, run_trials_sharded

    mesh = make_mesh({"dp": n})
    if log:
        log.info("sweep", "chunks dp-sharded over devices", devices=n)

    def runner(cfg, keys):
        return run_trials_sharded(cfg, mesh, keys).trials

    return runner


@dataclasses.dataclass(frozen=True)
class SurfaceCell:
    """One (strategy × noise × size_l) grid point of an adversary
    surface, with the dispatch-decision manifest of the config that
    actually ran (kernel-plan attribution per cell)."""

    strategy: str
    p_depolarize: float
    p_measure_flip: float
    size_l: int
    result: SweepResult
    manifest: dict[str, Any] | None = None


def run_surface(
    cfg: QBAConfig,
    strategies: tuple[str, ...] | list[str],
    noise_points: list[tuple[float, float]],
    size_ls: list[int],
    n_chunks: int = 1,
    chunk_trials: int | None = None,
    checkpoint_dir: str | None = None,
    log: EventLog | None = None,
    runner=None,
    with_manifest: bool = True,
) -> list[SurfaceCell]:
    """The (strategy × noise × sizeL) adversary surface as ONE sharded
    Monte-Carlo: every cell is a :func:`run_sweep` over the same runner
    (dp-sharded over all visible devices when several are up — the
    ``parallel.montecarlo`` path), so the whole grid shares key-tree
    discipline, checkpoint format and placement independence.

    ``noise_points`` are ``(p_depolarize, p_measure_flip)`` pairs.  With
    ``checkpoint_dir``, each cell checkpoints to its own file (named by
    the cell coordinates) and a re-run resumes cell-by-cell.  With
    ``with_manifest``, each cell carries the dispatch-decision manifest
    collected around its own run — per-cell kernel attribution, since
    strategy changes the traced round program (forge-P is statically
    gated) and size_l changes the block plan.
    """
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs.manifest import collect_manifest

    cells: list[SurfaceCell] = []
    for strat in strategies:
        for p_dep, p_mf in noise_points:
            for size_l in size_ls:
                cfg_cell = dataclasses.replace(
                    cfg,
                    strategy=strat,
                    p_depolarize=p_dep,
                    p_measure_flip=p_mf,
                    size_l=size_l,
                )
                ckpt = None
                if checkpoint_dir:
                    os.makedirs(checkpoint_dir, exist_ok=True)
                    ckpt = os.path.join(
                        checkpoint_dir,
                        f"surface_{strat}_p{p_dep}_q{p_mf}_L{size_l}.json",
                    )
                with record_decisions() as decisions:
                    res = run_sweep(
                        cfg_cell,
                        n_chunks=n_chunks,
                        chunk_trials=chunk_trials,
                        checkpoint=ckpt,
                        log=log,
                        runner=runner,
                    )
                manifest = (
                    collect_manifest(
                        cfg_cell, command="surface", decisions=decisions
                    )
                    if with_manifest
                    else None
                )
                cells.append(
                    SurfaceCell(
                        strategy=strat,
                        p_depolarize=p_dep,
                        p_measure_flip=p_mf,
                        size_l=size_l,
                        result=res,
                        manifest=manifest,
                    )
                )
                if log:
                    log.info(
                        "surface",
                        "cell done",
                        strategy=strat,
                        p_depolarize=p_dep,
                        p_measure_flip=p_mf,
                        size_l=size_l,
                        success_rate=res.success_rate,
                    )
    return cells


def run_sweep(
    cfg: QBAConfig,
    n_chunks: int,
    chunk_trials: int | None = None,
    checkpoint: str | None = None,
    log: EventLog | None = None,
    timers: PhaseTimers | None = None,
    runner=None,
) -> SweepResult:
    """Run ``n_chunks`` batches of ``chunk_trials`` trials each.

    ``runner(cfg, keys) -> TrialResult`` defaults to the jitted vmap
    batch on one device, or to trials sharded over a ``dp`` mesh spanning
    all visible devices when there are several (and the chunk size
    divides the device count); the mesh-sharded runners in
    :mod:`qba_tpu.parallel` can also be partial-applied in explicitly.
    With ``checkpoint``, completed chunks are persisted after each chunk
    and skipped on re-run.  Results are placement-independent
    (tests/test_parallel.py), so resuming on different hardware
    reproduces the same sweep.
    """
    if chunk_trials is None:
        chunk_trials = cfg.trials

    # Opt-in persistent compilation cache: long sweeps re-enter the same
    # per-chunk program across resumes/processes, so a disk-cached
    # executable turns a tens-of-seconds recompile into a file read.
    # Strictly env-gated here — run_sweep is a library entry point, and
    # library code must not silently flip global JAX config (the CLI
    # tool surfaces enable it unconditionally, and the serving
    # subsystem promotes the whole thing to a first-class cache-dir
    # artifact; see :mod:`qba_tpu.compile_cache` and docs/SERVING.md).
    if os.environ.get("QBA_COMPILE_CACHE"):
        from qba_tpu.compile_cache import enable_compile_cache, xla_cache_dir

        enable_compile_cache(xla_cache_dir())

    loaded = load_checkpoint(checkpoint, cfg, chunk_trials) if checkpoint else []
    # A checkpoint may hold more chunks than this invocation asks for;
    # aggregate only the requested range (the file keeps the full set).
    chunks = [c for c in loaded if c.chunk < n_chunks]
    extra = [c for c in loaded if c.chunk >= n_chunks]
    done = {c.chunk for c in chunks}
    resumed = len(chunks)
    if log and resumed:
        log.info("sweep", "resumed from checkpoint", chunks=resumed, path=checkpoint)

    timers = timers or PhaseTimers()
    todo = [c for c in range(n_chunks) if c not in done]
    # Double-buffered pipeline: dispatch chunk k+1 before fetching chunk
    # k's results, so the host-side readback (expensive on tunneled
    # backends) overlaps the next chunk's device execution.  JAX's async
    # dispatch makes the in-flight window free; depth 2 bounds device
    # memory to two chunk batches.  Dispatch and readback are timed as
    # distinct phases ("dispatch"/"readback") so each phase's count equals
    # the number of chunks and per-chunk means stay honest; a finished
    # chunk is drained-and-checkpointed even if the next dispatch raises.
    in_flight: list[tuple[int, Any, float]] = []

    def drain_one() -> None:
        chunk, res, dispatch_s = in_flight.pop(0)
        t0 = timers.total("readback")
        with timers.time("readback", chunk=chunk) as sp:
            successes = int(np.sum(np.asarray(res.success)))
            overflow = bool(np.any(np.asarray(res.overflow)))
            # The np.asarray reads above ARE the host readback barrier
            # for this chunk's results (docs/PERF.md) — label the span.
            sp.fenced = True
        cr = ChunkResult(
            chunk=chunk,
            trials=chunk_trials,
            successes=successes,
            overflow=overflow,
            dispatch_s=dispatch_s,
            readback_s=timers.total("readback") - t0,
        )
        chunks.append(cr)
        if checkpoint:
            save_checkpoint(checkpoint, cfg, chunk_trials, chunks + extra)
        if log:
            log.info(
                "sweep",
                "chunk done",
                chunk=chunk,
                successes=cr.successes,
                trials=cr.trials,
            )

    try:
        for chunk in todo:
            if runner is None:
                # Lazy: a fully-checkpointed re-run never touches the
                # backend.
                runner = _default_runner(chunk_trials, log)
            keys = chunk_keys(cfg, chunk, chunk_trials)
            t0 = timers.total("dispatch")
            with timers.time("dispatch", chunk=chunk):
                res = runner(cfg, keys)
            in_flight.append((chunk, res, timers.total("dispatch") - t0))
            if len(in_flight) >= 2:
                drain_one()
    finally:
        # Preserve completed work if a dispatch fails mid-pipeline.
        while in_flight:
            drain_one()

    chunks.sort(key=lambda c: c.chunk)
    return SweepResult(cfg=cfg, chunks=tuple(chunks), resumed_chunks=resumed)
