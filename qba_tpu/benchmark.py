"""Shared Monte-Carlo measurement harness.

Used by both benchmark surfaces — ``python -m qba_tpu bench`` (the CLI)
and the repo-root ``bench.py`` gate script — so the chunk-split /
key-split / fence-at-end timing recipe exists exactly once.  The recipe
matters: on remote-tunnel backends only a host readback is a fence
(:func:`qba_tpu.backends.jax_backend.fence`), keys are regenerated per
rep so a result-caching backend cannot fake a 0-second run, and chunked
dispatch both respects the HBM ceiling of large configs and pipelines
better (docs/PERF.md).
"""

from __future__ import annotations

import dataclasses
import time

from qba_tpu.config import QBAConfig

# BASELINE.md config 5 as written (the "north star": nParties=33,
# sizeL=64, nDishonest=10, lossless), 1000 trials — THE shared literal
# for both gate surfaces (cli `--preset northstar` and bench.py's
# embedded gate metric).  Single batch: the round-4 pool donation +
# meta packing fit the whole 1000-trial batch in HBM (ceiling now
# >= 1024, docs/PERF.md round 4), and one batch measures ~33% faster
# than the round-3 250-trial chunking (9.9k vs 7.4k rounds/s honest).
NORTHSTAR = dict(n_parties=33, size_l=64, n_dishonest=10, trials=1000)
NORTHSTAR_CHUNK = 1000


def kernel_plan(cfg: QBAConfig, tp: int | None = None) -> dict:
    """Resolved per-kernel execution plan for benchmark attribution.

    One dict per config, embedded in the ``BENCH_r*.json`` rows so a
    measurement can be tied to the exact kernel path that produced it:

    - ``engine``: the resolved round engine.
    - ``variant``: verdict accept-path variant (tiled/fused engines).
    - ``verdict_block`` / ``rebuild_block``: packet-block sizes of the
      two-kernel tiled path (None where not applicable).
    - ``fused_block``: the fused kernel's output block size (None when
      the fused path is unavailable/demoted).
    - ``trial_pack``: trials folded per fused kernel grid (1 = no
      packing).
    - ``launches_per_round``: pallas_call launches each round costs —
      1 on the fused path, 2 on the tiled pair, 1 monolithic, 0 XLA;
      None on the megakernel (its launch is per TRIAL, not per round).
    - ``mega_block``: the trial megakernel's ``(decode, verdict)``
      block plan (None off the ``pallas_mega`` path or when it demotes
      on VMEM budget).
    - ``mega_gen``: where step-1 generation runs on the megakernel
      path — ``"gf2"`` when the in-VMEM GF(2) sweep is fused into the
      launch, ``"host"`` otherwise; None off the ``pallas_mega`` path.
    - ``launches_per_trial``: total pallas_call launches one trial
      costs under the resolved engine — the round-8 fixed-overhead
      attribution unit (1 on ``pallas_mega``, ``n_rounds`` fused,
      ``2 * n_rounds`` tiled, 0 XLA); the lint launch pin
      (:mod:`qba_tpu.analysis.launches`) proves this model against the
      traced jaxpr.

    With ``tp`` set (a party-sharded run on a dp×tp mesh) three more
    fields attribute the comms path, lifting the spmd demotions that
    used to live only in recorded warnings into the artifact:

    - ``tp``: the tp mesh width the row ran at.
    - ``tp_engine``: the engine the party-sharded dispatch resolves —
      including ``pallas_mega``, whose sharded variant runs the
      neighbor ring inside the one launch (it still demotes to
      ``pallas_fused`` when counters are requested or no sharded plan
      fits the reserved VMEM budget, and ``tp_demoted_from`` records
      the original).
    - ``tp_comms``: the resolved comms transport (``ring`` /
      ``all_gather``, :func:`qba_tpu.parallel.ring.resolve_tp_comms`).
    - ``tp_demoted_from``: the forced engine the sharded path demoted
      away from, or None.

    Every field is a cached compile-probe verdict (or a static plan
    off-TPU), so calling this after a measurement re-reads the memoized
    resolution the run actually used."""
    from qba_tpu.analysis.launches import LAUNCH_MODEL
    from qba_tpu.rounds.engine import resolve_round_engine

    engine = resolve_round_engine(cfg)
    plan = {
        "engine": engine,
        "variant": None,
        "verdict_block": None,
        "rebuild_block": None,
        "fused_block": None,
        "mega_block": None,
        "mega_gen": None,
        "trial_pack": 1,
        "launches_per_round": {"xla": 0, "pallas": 1}.get(engine, 2),
        "launches_per_trial": LAUNCH_MODEL.get(
            engine, lambda c: None
        )(cfg),
    }
    if engine == "pallas_mega":
        from qba_tpu.ops.round_kernel_tiled import (
            resolve_mega_block,
            resolve_mega_gen,
            resolve_trial_pack,
            resolve_verdict_variant,
        )

        plan["variant"] = resolve_verdict_variant(cfg)
        plan["launches_per_round"] = None
        mega = resolve_mega_block(cfg)
        plan["mega_block"] = mega
        plan["mega_gen"] = resolve_mega_gen(cfg)
        if mega is None or cfg.collect_counters:
            # run_trial demotes (VMEM budget / counters need the host
            # scan); attribute the fused path that actually runs.
            plan["launches_per_trial"] = LAUNCH_MODEL["pallas_fused"](
                cfg
            )
        else:
            plan["trial_pack"] = resolve_trial_pack(cfg)
    if engine in ("pallas_tiled", "pallas_fused"):
        from qba_tpu.ops.round_kernel_tiled import (
            resolve_rebuild_block,
            resolve_tiled_block,
            resolve_verdict_variant,
        )

        plan["variant"] = resolve_verdict_variant(cfg)
        plan["verdict_block"] = resolve_tiled_block(cfg)
        plan["rebuild_block"] = resolve_rebuild_block(cfg)
    if engine == "pallas_fused":
        from qba_tpu.ops.round_kernel_tiled import (
            resolve_fused_block,
            resolve_trial_pack,
        )

        pack = resolve_trial_pack(cfg)
        plan["fused_block"] = resolve_fused_block(cfg, trial_pack=pack)
        if plan["fused_block"] is None and pack != 1:
            # The packed plan failed to compile; the runner falls back
            # to the unpacked fused kernel (or tiled).  Attribute what
            # actually runs.
            pack = 1
            plan["fused_block"] = resolve_fused_block(cfg)
        plan["trial_pack"] = pack
        plan["launches_per_round"] = (
            1 if plan["fused_block"] is not None else 2
        )
    if tp is not None:
        import warnings as _warnings

        from qba_tpu.parallel.ring import resolve_tp_comms
        from qba_tpu.parallel.spmd import _resolve_spmd_engine

        with _warnings.catch_warnings():
            # The mega->fused demotion is recorded at dispatch; here it
            # is being ATTRIBUTED, not re-announced.
            _warnings.simplefilter("ignore")
            tp_engine = _resolve_spmd_engine(cfg, cfg.n_lieutenants // tp)
        plan["tp"] = tp
        plan["tp_engine"] = tp_engine
        plan["tp_comms"] = resolve_tp_comms(cfg)
        plan["tp_demoted_from"] = (
            cfg.round_engine
            if cfg.round_engine not in ("auto", tp_engine)
            else None
        )
    return plan


def engine_description(cfg: QBAConfig, tp: int | None = None) -> str:
    """Engine attribution string for benchmark artifacts: the resolved
    round engine, plus the verdict-kernel variant when a tiled-family
    engine runs, plus the trial-packing factor on the fused path (e.g.
    ``"pallas_tiled/group"``, ``"pallas_fused/group/pack4"``) — so a
    ``BENCH_r*.json`` row can be tied to the kernel path that produced
    it (the round-6 accept-path split and the round-7 fusion/packing
    split make the engine name alone ambiguous across machines: both
    are per-machine compile probes).

    With ``tp`` set the string names the party-sharded path instead —
    ``"spmd[tp=4]/pallas_fused/ring"`` — including the lifted
    mega demotion (``"spmd[tp=4]/pallas_fused(from mega)/ring"``), so
    multichip rows attribute the comms transport, not just the
    kernel."""
    if tp is not None:
        plan = kernel_plan(cfg, tp=tp)
        tp_engine = plan["tp_engine"]
        if plan["tp_demoted_from"] is not None:
            short = plan["tp_demoted_from"].removeprefix("pallas_")
            tp_engine = f"{tp_engine}(from {short})"
        return f"spmd[tp={tp}]/{tp_engine}/{plan['tp_comms']}"
    plan = kernel_plan(cfg)
    engine = plan["engine"]
    if engine == "pallas_mega":
        desc = f"{engine}/{plan['variant']}"
        if plan["mega_block"] is None:
            return desc + "/demoted-to-fused"
        if cfg.collect_counters:
            return desc + "/demoted-to-fused(counters)"
        if plan["mega_gen"] == "gf2":
            desc += "/gen-gf2"
        return desc + f"/pack{plan['trial_pack']}"
    if engine == "pallas_fused":
        desc = f"{engine}/{plan['variant']}"
        if plan["fused_block"] is None:
            return desc + "/demoted-to-tiled"
        return desc + f"/pack{plan['trial_pack']}"
    if engine == "pallas_tiled":
        return f"{engine}/{plan['variant']}"
    return engine


def qsim_description(cfg: QBAConfig) -> str:
    """Resource-generation attribution string, the qsim counterpart of
    :func:`engine_description`: which sampler family a ``resource_gen``
    measurement actually ran (e.g. ``"stabilizer/gf2-batched"``,
    ``"factorized/closed-form"``)."""
    if cfg.qsim_path == "stabilizer":
        return "stabilizer/gf2-batched"
    if cfg.qsim_path == "factorized":
        return "factorized/closed-form"
    if cfg.qsim_path == "dense_pallas":
        if cfg.total_qubits > _dense_cap():
            # generate_lists_dense(impl="auto") hands off past the cap.
            return "stabilizer/gf2-batched(auto)"
        return "dense/pallas"
    return "dense/xla"


def _dense_cap() -> int:
    from qba_tpu.config import DENSE_QUBIT_CAP

    return DENSE_QUBIT_CAP


def measure_resource_gen(
    cfg: QBAConfig,
    reps: int,
    *,
    warmup: bool = True,
):
    """Time ``reps`` full resource-generation batches: ``cfg.trials``
    independent list generations of ``cfg.size_l`` positions each,
    through the same :func:`~qba_tpu.qsim.generate_lists_for` dispatch
    the protocol engine calls (so the measurement attributes to the
    sampler the trial loop would actually run).

    Same recipe discipline as :func:`measure_batch`: fresh keys per rep
    (a result-caching backend cannot serve a 0-second rep), key
    generation fenced off the clock, one fence after the batch.

    Returns ``(rep_seconds, shots_per_rep)`` where a *shot* is one list
    position (``trials x size_l``) — the unit of the ``shots_per_sec``
    headline.
    """
    import jax

    from qba_tpu.backends.jax_backend import fence
    from qba_tpu.qsim import generate_lists_for

    if reps < 1:
        raise ValueError("reps must be >= 1")
    gen = jax.jit(jax.vmap(lambda k: generate_lists_for(cfg, k)))
    if warmup:
        fence(gen(jax.random.split(jax.random.key(cfg.seed), cfg.trials)))
    times = []
    for rep in range(reps):
        keys = jax.random.split(
            jax.random.key(cfg.seed + 1 + rep), cfg.trials
        )
        fence(keys)  # key generation off the clock
        t0 = time.perf_counter()
        out = gen(keys)
        fence(out)
        times.append(time.perf_counter() - t0)
    return times, cfg.trials * cfg.size_l


def measure_batch(
    cfg: QBAConfig,
    reps: int,
    chunk_trials: int | None = None,
    *,
    warmup: bool = True,
):
    """Time ``reps`` full Monte-Carlo batches of ``cfg.trials`` trials.

    ``chunk_trials`` splits each batch into sequential same-shape chunks
    (one compiled program); a partial final chunk rounds UP — the actual
    trial count is returned, and throughput must be computed against it.

    Returns ``(rep_seconds, n_run, results)``: per-rep wall times, the
    actual trials per rep, and the last rep's list of per-chunk
    :class:`~qba_tpu.backends.jax_backend.MonteCarloResult`.

    ``warmup=False`` skips the untimed compile/warmup batch — for
    callers that already warmed the jit cache and must keep the extra
    execution out of a profiler trace (see cli ``--profile-dir``).
    """
    import jax

    from qba_tpu.backends.jax_backend import fence, run_trials, trial_keys

    if reps < 1:
        raise ValueError("reps must be >= 1")
    chunk = chunk_trials or cfg.trials
    n_chunks = -(-cfg.trials // chunk)
    cfg_chunk = dataclasses.replace(cfg, trials=chunk)

    def run_chunk(keys_chunk):
        return _run_trials_named(run_trials, cfg, cfg_chunk, keys_chunk)

    if warmup:
        fence(run_chunk(trial_keys(cfg_chunk)))  # compile
    times, results = [], None
    for rep in range(reps):
        keys = jax.random.split(
            jax.random.key(cfg.seed + 1 + rep), n_chunks * chunk
        )
        fence(keys)  # key generation off the clock
        t0 = time.perf_counter()
        results = [
            run_chunk(keys[i * chunk : (i + 1) * chunk])
            for i in range(n_chunks)
        ]
        fence(results)  # last leaf = last chunk -> all chunks done
        times.append(time.perf_counter() - t0)
    return times, n_chunks * chunk, results


def _run_trials_named(run_trials, cfg, cfg_chunk, keys_chunk):
    """``run_trials`` with the KI-2 HBM-ceiling diagnostic attached."""
    chunk = cfg_chunk.trials
    try:
        return run_trials(cfg_chunk, keys_chunk)
    except Exception as e:  # name the batch-size HBM ceiling (KI-2)
        msg = str(e)
        if "Ran out of memory in memory space hbm" not in msg:
            raise
        # Only the compile-time verdict is the hard per-config
        # ceiling; a runtime RESOURCE_EXHAUSTED with the same
        # marker can be transient pressure (HBM held elsewhere).
        compile_time = "compile permanent error" in msg
        raise RuntimeError(
            f"single-batch Monte-Carlo of {chunk} trials exceeds "
            f"TPU HBM {'at compile time' if compile_time else 'at run time'} "
            f"for this config (n_parties={cfg.n_parties}, "
            f"size_l={cfg.size_l}, n_dishonest={cfg.n_dishonest}). "
            + (
                "This is the real batch ceiling, not a compiler "
                "bug — on a remote-tunnel backend the OOM arrives "
                "disguised as a compile-helper exit-1 "
                "(docs/KNOWN_ISSUES.md KI-2; measured at the "
                "north-star scale: 1088 trials fit in 15.75 GB, "
                "1152 overflow by 1.8 GB).  "
                if compile_time
                else "If other processes hold HBM, freeing them may "
                "suffice (docs/KNOWN_ISSUES.md KI-2 documents the "
                "per-config compile-time ceiling).  "
            )
            + "Split the batch with chunk_trials / --chunk-trials."
        ) from e


def measure_device_batch(
    cfg: QBAConfig,
    pairs: int = 3,
    reps_lo: int = 1,
    reps_hi: int = 5,
    chunk_trials: int | None = None,
    *,
    warmup: bool = True,
):
    """Slope-based DEVICE-side batch seconds (VERDICT r4 item 4).

    On a remote-tunnel backend every fenced wall time includes a
    ~60-100 ms result fetch with tens of ms of jitter — ~40% spread at
    the headline config, which :func:`measure_batch` honestly reports
    but cannot decompose.  This measures the device-side sustained time
    per batch by the slope trick: dispatch ``r`` same-shape batches
    back-to-back with ONE final fence, for ``r = reps_lo`` and
    ``r = reps_hi``; the difference quotient

        (T(reps_hi) - T(reps_lo)) / (reps_hi - reps_lo)

    cancels the constant dispatch + fetch overhead, leaving the
    per-batch device execution time (host enqueue overlaps device
    execution on the async stream, so sustained throughput is the
    honest interpretation).  Each of ``pairs`` repetitions draws fresh
    keys (a result-caching backend cannot fake the slope).

    Returns ``(device_seconds_per_batch: list[float], n_run)`` — one
    slope estimate per pair; callers take the median and quote the
    spread.
    """
    import jax

    from qba_tpu.backends.jax_backend import fence, run_trials, trial_keys

    if pairs < 1:
        raise ValueError("pairs must be >= 1")
    if not 1 <= reps_lo < reps_hi:
        raise ValueError("need 1 <= reps_lo < reps_hi")
    chunk = chunk_trials or cfg.trials
    n_chunks = -(-cfg.trials // chunk)
    cfg_chunk = dataclasses.replace(cfg, trials=chunk)
    if warmup:
        fence(
            _run_trials_named(
                run_trials, cfg, cfg_chunk, trial_keys(cfg_chunk)
            )
        )

    def timed_chain(r: int, tag: int) -> float:
        keys = jax.random.split(
            jax.random.key(cfg.seed + tag), r * n_chunks * chunk
        )
        fence(keys)  # key generation off the clock
        t0 = time.perf_counter()
        res = None
        for i in range(r * n_chunks):
            res = _run_trials_named(
                run_trials, cfg, cfg_chunk,
                keys[i * chunk : (i + 1) * chunk],
            )
        fence(res)  # single stream: last batch done => all done
        return time.perf_counter() - t0

    # Throwaway chain at FULL depth: the first dispatch burst after
    # (re)warming pays one-off tunnel/queue setup proportional to the
    # chain length — a short throwaway leaves the first long chain's
    # T_hi inflated and corrupts the first slope (observed: 2-4x
    # outliers on the first pair at the headline config).
    timed_chain(reps_hi, 999)
    slopes = []
    for p in range(pairs):
        t_lo = timed_chain(reps_lo, 1001 + 2 * p)
        t_hi = timed_chain(reps_hi, 1002 + 2 * p)
        slopes.append((t_hi - t_lo) / (reps_hi - reps_lo))
    return slopes, n_chunks * chunk
