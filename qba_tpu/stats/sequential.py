"""Anytime-valid stopping rules over streaming binomial counts.

Two rules, both safe to consult after *every* chunk without inflating
error rates (the "anytime validity" docs/STATS.md spells out):

* :class:`SPRT` — Wald's sequential probability ratio test for
  ``success_rate ⋛ threshold`` hypotheses, with an indifference region
  ``threshold ± delta``.  Error rates are bounded by the classical
  boundary choice ``A = (1-β)/α``, ``B = β/(1-α)``.
* :class:`MixtureMartingaleCI` — a Beta(½,½)-mixture martingale
  confidence sequence; its running interval covers the true rate at
  every sample size simultaneously with probability ≥ confidence, so a
  "stop when the CI is narrow enough" rule stays honest.

Each rule emits a typed :class:`StopDecision` when it fires.  Rules are
pure host-side arithmetic over integer counts — deterministic given the
observation sequence, which the allocator keeps deterministic given seed
and arrival order.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

from qba_tpu.stats.estimators import RateEstimate, rate_estimate

__all__ = [
    "MixtureMartingaleCI",
    "SPRT",
    "StopDecision",
]

#: StopDecision.reason vocabulary (docs/STATS.md).
STOP_REASONS = (
    "decided_above",  # SPRT accepted p >= threshold + delta
    "decided_below",  # SPRT accepted p <= threshold - delta
    "ci_width",  # confidence-sequence width reached the target
    "budget_exhausted",  # trial budget ran out before the rule fired
)


@dataclasses.dataclass(frozen=True)
class StopDecision:
    """Why a sequential run stopped, after how many trials, and at what
    bound.  ``bound`` is rule-specific: the crossed log-likelihood-ratio
    boundary for SPRT, the achieved CI width for the width rule, and the
    remaining CI width for ``budget_exhausted``."""

    reason: str
    n_trials: int
    bound: float
    threshold: float | None = None
    estimate: RateEstimate | None = None

    def __post_init__(self):
        if self.reason not in STOP_REASONS:
            raise ValueError(
                f"unknown stop reason {self.reason!r}; "
                f"choose from {STOP_REASONS}"
            )

    @property
    def decided(self) -> bool:
        return self.reason in ("decided_above", "decided_below")

    def to_json(self) -> dict[str, Any]:
        return {
            "reason": self.reason,
            "n_trials": self.n_trials,
            "bound": self.bound,
            "threshold": self.threshold,
            "estimate": (
                self.estimate.to_json() if self.estimate is not None else None
            ),
        }


def _clip_p(p: float) -> float:
    return min(max(p, 1e-9), 1.0 - 1e-9)


class MixtureMartingaleCI:
    """Beta(½,½)-mixture martingale confidence sequence.

    For a candidate rate ``p`` the mixture likelihood ratio after ``k``
    successes in ``n`` trials is

        ``M_n(p) = B(k+½, n-k+½) / B(½, ½) / (p^k (1-p)^(n-k))``

    which is a nonnegative martingale with ``E[M] = 1`` when ``p`` is the
    true rate; by Ville's inequality ``P[sup_n M_n(p) >= 1/alpha] <=
    alpha``.  The running confidence set ``{p : M_n(p) < 1/alpha}`` is an
    interval (log M is convex in ``logit p``), found here by bisection
    from the MLE outward.  Optionally doubles as a stopping rule: with
    ``target_width`` set, :meth:`decision` fires when the interval is
    narrow enough.
    """

    def __init__(
        self, confidence: float = 0.95, target_width: float | None = None
    ):
        if not 0.0 < confidence < 1.0:
            raise ValueError(
                f"confidence must be in (0, 1), got {confidence}"
            )
        if target_width is not None and not 0.0 < target_width <= 1.0:
            raise ValueError(
                f"target_width must be in (0, 1], got {target_width}"
            )
        self.confidence = confidence
        self.target_width = target_width
        self.k = 0
        self.n = 0

    def observe(self, k: int, n: int) -> None:
        if not 0 <= k <= n:
            raise ValueError(f"need 0 <= k <= n, got k={k}, n={n}")
        self.k += int(k)
        self.n += int(n)

    def width_at(self, k: int, n: int) -> float:
        """Interval width at totals ``(k, n)`` without touching this
        rule's state — the probe the device stop tables are built from
        (stats/device.py).  Evaluates exactly the arithmetic
        :meth:`decision` consults, so "``width_at(k, n) <=
        target_width``" IS the host stopping predicate at those totals.
        """
        probe = MixtureMartingaleCI(
            confidence=self.confidence, target_width=self.target_width
        )
        probe.k, probe.n = int(k), int(n)
        lo, hi = probe.interval()
        return hi - lo

    def interval_at(self, k: int, n: int) -> tuple[float, float]:
        """The running interval at totals ``(k, n)``, state-free (the
        straddle probe used by the device allocator's verification
        tests)."""
        probe = MixtureMartingaleCI(
            confidence=self.confidence, target_width=self.target_width
        )
        probe.k, probe.n = int(k), int(n)
        return probe.interval()

    def _log_mixture(self, p: float) -> float:
        """log M_n(p) for the current counts."""
        a = b = 0.5
        k, n = self.k, self.n
        p = _clip_p(p)
        lbeta = math.lgamma(k + a) + math.lgamma(n - k + b) - math.lgamma(
            n + a + b
        )
        lbeta0 = math.lgamma(a) + math.lgamma(b) - math.lgamma(a + b)
        return (
            lbeta - lbeta0 - (k * math.log(p) + (n - k) * math.log1p(-p))
        )

    def interval(self) -> tuple[float, float]:
        """The running confidence interval ``{p : M_n(p) < 1/alpha}``."""
        if self.n == 0:
            return (0.0, 1.0)
        crit = math.log(1.0 / (1.0 - self.confidence))
        p_hat = self.k / self.n
        # log M is minimized at the MLE and increases monotonically
        # toward each endpoint, so each boundary is a 1-d bisection.
        if self._log_mixture(p_hat) >= crit:
            # Degenerate (tiny n): the whole set may be empty around the
            # MLE under clipping; report the vacuous interval.
            return (0.0, 1.0)

        def boundary(lo: float, hi: float, rising_at_hi: bool) -> float:
            for _ in range(80):
                mid = 0.5 * (lo + hi)
                if (self._log_mixture(mid) >= crit) == rising_at_hi:
                    hi = mid
                else:
                    lo = mid
            return 0.5 * (lo + hi)

        lower = (
            0.0
            if self._log_mixture(0.0) < crit
            else boundary(0.0, p_hat, rising_at_hi=False)
        )
        upper = (
            1.0
            if self._log_mixture(1.0) < crit
            else boundary(p_hat, 1.0, rising_at_hi=True)
        )
        return (lower, upper)

    def estimate(self) -> RateEstimate:
        lo, hi = self.interval()
        return RateEstimate(
            k=self.k,
            n=self.n,
            rate=self.k / self.n if self.n else float("nan"),
            lo=lo,
            hi=hi,
            method="mixture_martingale",
            confidence=self.confidence,
        )

    def decision(self) -> StopDecision | None:
        """Fires when the running CI width reaches ``target_width``."""
        if self.target_width is None or self.n == 0:
            return None
        est = self.estimate()
        if est.width <= self.target_width:
            return StopDecision(
                reason="ci_width",
                n_trials=self.n,
                bound=est.width,
                estimate=est,
            )
        return None

    def exhausted(self) -> StopDecision:
        """The budget ran out first; report the CI actually achieved."""
        est = self.estimate()
        return StopDecision(
            reason="budget_exhausted",
            n_trials=self.n,
            bound=est.width,
            estimate=est,
        )


class SPRT:
    """Wald's SPRT for ``H0: p <= threshold - delta`` vs
    ``H1: p >= threshold + delta``.

    The log-likelihood ratio ``LLR = sum log f(x; p1)/f(x; p0)`` with
    ``p0 = threshold - delta``, ``p1 = threshold + delta`` is compared
    against ``log((1-beta)/alpha)`` (accept H1: ``decided_above``) and
    ``log(beta/(1-alpha))`` (accept H0: ``decided_below``).  Inside the
    indifference region ``(p0, p1)`` either decision is acceptable; the
    test's expected sample size there is largest.

    The rule also owns a :class:`MixtureMartingaleCI` fed the same
    counts, so the estimate reported at stop carries an *anytime-valid*
    interval — a fixed-n Wilson interval at a data-dependent stopping
    time would overstate precision (docs/STATS.md).
    """

    def __init__(
        self,
        threshold: float,
        alpha: float = 0.05,
        beta: float = 0.05,
        delta: float = 0.05,
        confidence: float | None = None,
    ):
        if not 0.0 < threshold < 1.0:
            raise ValueError(
                f"threshold must be in (0, 1), got {threshold}"
            )
        if not 0.0 < alpha < 1.0 or not 0.0 < beta < 1.0:
            raise ValueError(f"alpha/beta must be in (0, 1): {alpha}, {beta}")
        if delta <= 0.0:
            raise ValueError(f"delta must be positive, got {delta}")
        self.threshold = threshold
        self.alpha = alpha
        self.beta = beta
        self.delta = delta
        self.p0 = _clip_p(threshold - delta)
        self.p1 = _clip_p(threshold + delta)
        self.log_a = math.log((1.0 - beta) / alpha)  # accept H1 above this
        self.log_b = math.log(beta / (1.0 - alpha))  # accept H0 below this
        self._s = math.log(self.p1 / self.p0)  # per-success increment
        self._f = math.log((1.0 - self.p1) / (1.0 - self.p0))  # per-failure
        self.llr = 0.0
        self.n = 0
        self.k = 0
        self.ci = MixtureMartingaleCI(
            confidence=confidence if confidence is not None else 1.0 - alpha
        )

    def llr_at(self, k: int, n: int) -> float:
        """The LLR at totals ``(k, n)`` — a pure function of the counts.
        :meth:`observe` keeps ``self.llr`` in exactly this totals form
        (not a per-chunk float accumulation), so the host stopping
        predicate is path-independent and the device stop tables
        (stats/device.py) can reproduce it exactly."""
        return k * self._s + (n - k) * self._f

    def observe(self, k: int, n: int) -> None:
        """Fold a chunk's counts into the running LLR (the per-trial LLR
        is linear in the success count, so chunk aggregation is exact;
        the stored value is recomputed from totals — see
        :meth:`llr_at`)."""
        if not 0 <= k <= n:
            raise ValueError(f"need 0 <= k <= n, got k={k}, n={n}")
        self.n += int(n)
        self.k += int(k)
        self.llr = self.llr_at(self.k, self.n)
        self.ci.observe(k, n)

    def decision(self) -> StopDecision | None:
        if self.n == 0:
            return None
        if self.llr >= self.log_a:
            return StopDecision(
                reason="decided_above",
                n_trials=self.n,
                bound=self.log_a,
                threshold=self.threshold,
                estimate=self.ci.estimate(),
            )
        if self.llr <= self.log_b:
            return StopDecision(
                reason="decided_below",
                n_trials=self.n,
                bound=self.log_b,
                threshold=self.threshold,
                estimate=self.ci.estimate(),
            )
        return None

    def exhausted(self) -> StopDecision:
        est = self.ci.estimate()
        return StopDecision(
            reason="budget_exhausted",
            n_trials=self.n,
            bound=self.llr,
            threshold=self.threshold,
            estimate=est,
        )

    def estimate(self) -> RateEstimate:
        return (
            self.ci.estimate()
            if self.n
            else rate_estimate(0, 0, confidence=self.ci.confidence)
        )
