"""The precision-target grammar: one string, parsed once, shared by
``run_sweep``, ``run_surface``, the CLI, and serve requests.

Two target kinds::

    decide vs 1/3                  # SPRT vs a threshold, 95% default
    decide vs 0.5 @ 99%            # explicit confidence
    decide vs 1/3 +-0.02           # explicit indifference half-width
    ci_width<=0.002 @ 95%          # mixture-martingale width rule

Thresholds accept decimals or simple fractions (``1/3`` — the paper's
``nDishonest < nParties/3`` boundary is the motivating case).  The
parsed :class:`Target` is frozen and JSON-serializable so manifests and
checkpoints can carry the *spec*, and :meth:`Target.make_rule`
constructs a fresh stopping rule per cell/request.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any

from qba_tpu.stats.sequential import SPRT, MixtureMartingaleCI, _clip_p

__all__ = ["Target", "parse_target"]

#: Default indifference half-width for ``decide`` targets without an
#: explicit ``+-d``: wide enough that the paper-boundary cells (true
#: rates well away from 1/3) decide in a handful of chunks.
DEFAULT_DELTA = 0.05
DEFAULT_CONFIDENCE = 0.95

_DECIDE_RE = re.compile(
    r"^decide\s+vs\s+(?P<thresh>[0-9./]+)"
    r"(?:\s*\+-\s*(?P<delta>[0-9.]+))?"
    r"(?:\s*@\s*(?P<conf>[0-9.]+)\s*%)?$"
)
_WIDTH_RE = re.compile(
    r"^ci_width\s*<=\s*(?P<width>[0-9.]+)"
    r"(?:\s*@\s*(?P<conf>[0-9.]+)\s*%)?$"
)


def _parse_number(text: str, what: str) -> float:
    """A decimal or a simple fraction like ``1/3``."""
    if "/" in text:
        num, _, den = text.partition("/")
        try:
            return float(num) / float(den)
        except (ValueError, ZeroDivisionError):
            raise ValueError(f"bad {what} {text!r}") from None
    try:
        return float(text)
    except ValueError:
        raise ValueError(f"bad {what} {text!r}") from None


@dataclasses.dataclass(frozen=True)
class Target:
    """A parsed precision target.  ``kind`` is ``"decide"`` or
    ``"ci_width"``; ``spec`` keeps the original string for manifests."""

    kind: str
    confidence: float
    spec: str
    threshold: float | None = None  # decide only
    delta: float = DEFAULT_DELTA  # decide only
    width: float | None = None  # ci_width only

    def make_rule(self):
        """A fresh stopping rule (one per cell / per serve request —
        rules are stateful accumulators and must not be shared)."""
        if self.kind == "decide":
            alpha = 1.0 - self.confidence
            return SPRT(
                threshold=self.threshold,
                alpha=alpha,
                beta=alpha,
                delta=self.delta,
                confidence=self.confidence,
            )
        return MixtureMartingaleCI(
            confidence=self.confidence, target_width=self.width
        )

    def planning_trials(self, budget: int) -> int:
        """A-priori trial price of this target for capacity planning
        (the fleet admission layer, docs/SERVING.md "Fleet").

        Deterministic, pure arithmetic, and deliberately a *planning
        estimate* rather than a guarantee — ``budget`` stays the hard
        ceiling and early stops release the difference:

        * ``decide`` — Wald's zero-drift expected-sample-size
          approximation at the indifference boundary ``p = threshold``
          (the worst case): ``E[N] ≈ -log_a · log_b / E[Z²]`` where
          ``Z`` is the per-trial log-likelihood-ratio increment.
        * ``ci_width`` — the anytime Hoeffding-style fixed point
          ``n = (log(1/α) + log(n+1)) / (2 (w/2)²)`` for the mixture
          sequence to reach half-width ``w/2``.
        """
        if budget < 1:
            raise ValueError(f"budget must be >= 1, got {budget}")
        alpha = 1.0 - self.confidence
        if self.kind == "decide":
            p0 = _clip_p(self.threshold - self.delta)
            p1 = _clip_p(self.threshold + self.delta)
            s = math.log(p1 / p0)
            f = math.log((1.0 - p1) / (1.0 - p0))
            log_a = math.log((1.0 - alpha) / alpha)
            log_b = math.log(alpha / (1.0 - alpha))
            p = self.threshold
            second_moment = p * s * s + (1.0 - p) * f * f
            expected = -log_a * log_b / second_moment
        else:
            half = self.width / 2.0
            expected = 1.0
            for _ in range(32):
                expected = (
                    math.log(1.0 / alpha) + math.log(expected + 1.0)
                ) / (2.0 * half * half)
        return max(1, min(budget, math.ceil(expected)))

    def to_json(self) -> dict[str, Any]:
        return {
            "kind": self.kind,
            "confidence": self.confidence,
            "threshold": self.threshold,
            "delta": self.delta if self.kind == "decide" else None,
            "width": self.width,
            "spec": self.spec,
        }


def parse_target(spec: str) -> Target:
    """Parse a target string (grammar in the module docstring).

    Raises ``ValueError`` on anything unrecognized — serve surfaces the
    message in the request's error result, the CLI at argparse time.
    """
    text = spec.strip()
    m = _DECIDE_RE.match(text)
    if m:
        threshold = _parse_number(m.group("thresh"), "threshold")
        if not 0.0 < threshold < 1.0:
            raise ValueError(
                f"decide threshold must be in (0, 1), got {threshold}"
            )
        delta = (
            float(m.group("delta")) if m.group("delta") else DEFAULT_DELTA
        )
        conf = (
            float(m.group("conf")) / 100.0
            if m.group("conf")
            else DEFAULT_CONFIDENCE
        )
        if not 0.0 < conf < 1.0:
            raise ValueError(f"confidence must be in (0, 100)%, got {conf}")
        return Target(
            kind="decide",
            confidence=conf,
            threshold=threshold,
            delta=delta,
            spec=text,
        )
    m = _WIDTH_RE.match(text)
    if m:
        width = float(m.group("width"))
        if not 0.0 < width <= 1.0:
            raise ValueError(f"ci width must be in (0, 1], got {width}")
        conf = (
            float(m.group("conf")) / 100.0
            if m.group("conf")
            else DEFAULT_CONFIDENCE
        )
        if not 0.0 < conf < 1.0:
            raise ValueError(f"confidence must be in (0, 100)%, got {conf}")
        return Target(kind="ci_width", confidence=conf, width=width, spec=text)
    raise ValueError(
        f"unrecognized target {spec!r}; expected 'decide vs <p> [+-d] "
        f"[@ NN%]' or 'ci_width<=<w> [@ NN%]'"
    )
