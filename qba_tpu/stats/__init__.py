"""Sequential statistics + adaptive trial allocation (docs/STATS.md).

The host-side statistics engine every Monte-Carlo path feeds:
:mod:`~qba_tpu.stats.estimators` turns chunk counts into certified rates
(point estimate + CI), :mod:`~qba_tpu.stats.sequential` provides
anytime-valid stopping rules, :mod:`~qba_tpu.stats.targets` parses the
shared ``target=`` grammar, and :mod:`~qba_tpu.stats.allocate` spends a
shared chunk budget across a cell grid where the answer is least known.
:mod:`~qba_tpu.stats.device` compiles the stopping predicate into the
integer threshold tables the device-resident ``lax.while_loop`` consults
(docs/STATS.md "Device-resident stopping").
"""

from qba_tpu.stats.allocate import AdaptiveAllocator
from qba_tpu.stats.device import stop_tables
from qba_tpu.stats.estimators import (
    RateEstimate,
    StreamingRate,
    SweepEstimators,
    clopper_pearson_ci,
    rate_estimate,
    round_histogram,
    success_rate,
    wilson_ci,
)
from qba_tpu.stats.sequential import SPRT, MixtureMartingaleCI, StopDecision
from qba_tpu.stats.targets import Target, parse_target

__all__ = [
    "AdaptiveAllocator",
    "MixtureMartingaleCI",
    "RateEstimate",
    "SPRT",
    "StopDecision",
    "StreamingRate",
    "SweepEstimators",
    "Target",
    "clopper_pearson_ci",
    "parse_target",
    "rate_estimate",
    "round_histogram",
    "stop_tables",
    "success_rate",
    "wilson_ci",
]
