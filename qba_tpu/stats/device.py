"""Host-exact stop tables for the device-resident sequential loop.

The device-resident targeted sweep (``run_sweep(dispatch="device")``,
docs/STATS.md "Device-resident stopping") carries only integer counts
through a ``lax.while_loop`` — the stopping predicate must therefore be
expressible over ``(cumulative successes K, chunks completed i)`` with
nothing but integer compares.  Both PR 10 rules allow it, because their
decisions are pure functions of the totals:

* :class:`~qba_tpu.stats.sequential.SPRT` — the aggregate LLR
  ``K·s + (N−K)·f`` is monotone nondecreasing in ``K`` (``s>0>f``), so
  each boundary crossing is a single integer threshold on ``K``;
* :class:`~qba_tpu.stats.sequential.MixtureMartingaleCI` — the interval
  width at ``(K, N)`` is unimodal in ``K`` (widest near ``N/2``,
  pinned per-``N`` by tests/test_device_loop.py), so the fire set
  ``{K : width ≤ target}`` is a pair of end intervals.

:func:`stop_tables` precomputes, for every possible chunk count
``i ∈ [0, n_chunks]`` with ``N = i·chunk_trials``, the thresholds
``lo[i]``/``hi[i]`` such that the host rule fires at totals ``(K, N)``
iff ``K <= lo[i]`` or ``K >= hi[i]``.  Each threshold is found by
bisection over ``K`` **evaluating the host rule's own float
arithmetic** (:meth:`SPRT.llr_at` / :meth:`MixtureMartingaleCI.width_at`),
so the device predicate agrees with the host loop's ``rule.decision()``
at every reachable count — the bit-identity bar of ROADMAP item 3.

Sentinels: ``lo[i] = -1`` / ``hi[i] = N+1`` mean "never fires at this
``i``" (no cumulative count can be ``<= -1`` or ``>= N+1``).  Index 0
always holds sentinels — a rule with zero observations never fires,
and the device loop must run at least one chunk, like the host loop.

Also here: :func:`device_ci_interval`, the traced float32 mixture-CI
bisection the device allocator uses to ORDER cells (widest-first
tiering).  Scheduling order tolerates float32 — per-cell STOP decisions
always go through the exact integer tables above (docs/STATS.md).
"""

from __future__ import annotations

import numpy as np

from qba_tpu.stats.sequential import SPRT, MixtureMartingaleCI
from qba_tpu.stats.targets import Target

__all__ = ["stop_tables", "device_ci_interval"]


def _bisect_threshold(fires, lo_k: int, hi_k: int, first_true: bool) -> int:
    """Boundary of a monotone indicator over the integer range
    ``[lo_k, hi_k]``.  ``first_true=True``: smallest K with
    ``fires(K)`` given the indicator is nondecreasing in K (caller has
    checked ``fires(hi_k)``); ``first_true=False``: largest K with
    ``fires(K)`` given it is nonincreasing (caller has checked
    ``fires(lo_k)``)."""
    lo, hi = lo_k, hi_k
    if first_true:
        while lo < hi:
            mid = (lo + hi) // 2
            if fires(mid):
                hi = mid
            else:
                lo = mid + 1
        return lo
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if fires(mid):
            lo = mid
        else:
            hi = mid - 1
    return lo


def _decide_thresholds(rule: SPRT, n: int) -> tuple[int, int]:
    """(lo, hi) stop thresholds for the SPRT at total trials ``n``.
    ``llr_at(K, n)`` is monotone nondecreasing in K, and float rounding
    preserves monotonicity (each term is a monotone product), so both
    crossings are clean bisections on the host's own arithmetic."""
    lo, hi = -1, n + 1
    if rule.llr_at(n, n) >= rule.log_a:
        hi = _bisect_threshold(
            lambda k: rule.llr_at(k, n) >= rule.log_a, 0, n, first_true=True
        )
    if rule.llr_at(0, n) <= rule.log_b:
        lo = _bisect_threshold(
            lambda k: rule.llr_at(k, n) <= rule.log_b, 0, n, first_true=False
        )
    return lo, hi


def _width_thresholds(rule: MixtureMartingaleCI, n: int) -> tuple[int, int]:
    """(lo, hi) stop thresholds for the width rule at total trials
    ``n``: fire iff ``width_at(K, n) <= target_width``.  Width is
    unimodal in K (widest near n/2), so the fire set is the two end
    intervals; each boundary is a bisection on the half-range."""
    w = rule.target_width
    mid = n // 2
    if rule.width_at(mid, n) <= w and rule.width_at(mid + (n % 2), n) <= w:
        # Fires even at the widest counts: every K stops.
        return n, 0
    lo, hi = -1, n + 1
    if rule.width_at(0, n) <= w:
        lo = _bisect_threshold(
            lambda k: rule.width_at(k, n) <= w, 0, mid, first_true=False
        )
    if rule.width_at(n, n) <= w:
        hi = _bisect_threshold(
            lambda k: rule.width_at(k, n) <= w, mid, n, first_true=True
        )
    return lo, hi


def stop_tables(
    target: Target, n_chunks: int, chunk_trials: int
) -> tuple[np.ndarray, np.ndarray]:
    """Integer stop thresholds on cumulative successes, one row per
    possible chunk count: after ``i`` chunks (``N = i·chunk_trials``
    trials) the host rule fires iff ``K <= lo[i]`` or ``K >= hi[i]``.

    Exact by construction: every threshold is located by bisection over
    the host rule's own decision arithmetic at those totals (monotone
    in K for the SPRT LLR; unimodal for the CI width), so the device
    ``while_loop`` condition stops at exactly the chunk boundary the
    host loop's per-chunk ``rule.decision()`` would
    (tests/test_device_loop.py pins the full-table equivalence).
    """
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    if chunk_trials < 1:
        raise ValueError(f"chunk_trials must be >= 1, got {chunk_trials}")
    rule = target.make_rule()
    lo = np.full(n_chunks + 1, -1, dtype=np.int32)
    hi = np.zeros(n_chunks + 1, dtype=np.int32)
    hi[0] = 1  # sentinel: N = 0, no count reaches K >= 1
    for i in range(1, n_chunks + 1):
        n = i * chunk_trials
        if target.kind == "decide":
            lo_i, hi_i = _decide_thresholds(rule, n)
        else:
            lo_i, hi_i = _width_thresholds(rule, n)
        lo[i], hi[i] = lo_i, hi_i
    return lo, hi


def device_ci_interval(k, n, confidence: float, iters: int = 60):
    """Traced float32 mixture-martingale interval at totals ``(k, n)``
    — the same Beta(½,½) mixture and MLE-outward bisection as
    :meth:`MixtureMartingaleCI.interval`, expressed in jnp so the
    device allocator can order cells widest-first **on device**.

    Used ONLY for scheduling priority inside the single-dispatch
    adaptive surface: float32 endpoints may differ from the host's
    float64 interval in the last ulps, which can reorder near-tied
    cells but never changes a stop decision (those go through the
    exact integer :func:`stop_tables`).  Returns ``(lo, hi)`` scalars;
    ``n == 0`` yields the vacuous ``(0, 1)``.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.scipy.special import gammaln

    k = jnp.asarray(k, jnp.float32)
    n = jnp.asarray(n, jnp.float32)
    a = b = 0.5
    crit = float(np.log(1.0 / (1.0 - confidence)))
    lbeta = gammaln(k + a) + gammaln(n - k + b) - gammaln(n + a + b)
    lbeta0 = float(np.log(np.pi))  # log B(1/2, 1/2)

    def log_mixture(p):
        p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
        return lbeta - lbeta0 - (k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    p_hat = jnp.where(n > 0, k / jnp.maximum(n, 1.0), 0.5)

    def boundary(lo0, hi0, rising_at_hi):
        def step(_, bounds):
            lo, hi = bounds
            mid = 0.5 * (lo + hi)
            cross = (log_mixture(mid) >= crit) == rising_at_hi
            return (jnp.where(cross, lo, mid), jnp.where(cross, mid, hi))

        lo, hi = lax.fori_loop(0, iters, step, (lo0, hi0))
        return 0.5 * (lo + hi)

    lower = jnp.where(
        log_mixture(jnp.float32(0.0)) < crit,
        jnp.float32(0.0),
        boundary(jnp.float32(0.0), p_hat, False),
    )
    upper = jnp.where(
        log_mixture(jnp.float32(1.0)) < crit,
        jnp.float32(1.0),
        boundary(p_hat, jnp.float32(1.0), True),
    )
    degenerate = (n == 0) | (log_mixture(p_hat) >= crit)
    lower = jnp.where(degenerate, jnp.float32(0.0), lower)
    upper = jnp.where(degenerate, jnp.float32(1.0), upper)
    return lower, upper
