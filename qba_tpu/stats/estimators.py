"""Streaming sufficient statistics over :class:`ChunkResult` streams.

Every Monte-Carlo path in the repo reduces a chunk of trials to the same
sufficient statistics on the host side of the readback — a success count,
an overflow count, and (when decisions are returned) a first-accept-round
counter vector.  This module owns the step from those counts to *certified*
rates: point estimate plus a binomial confidence interval, computed the
same way whether the counts came from ``run_sweep``, a surface cell, a
serve request, or a study script.  Everything here is pure Python/NumPy on
plain integers — no JAX, no device state — so every engine/backend feeds
it identically and the numbers in a manifest never depend on which kernel
produced the trials.

Two interval families:

* **Wilson** (:func:`wilson_ci`) — the score interval.  Closed form,
  excellent coverage for moderate ``n``, and the repo's historical choice
  (``obs/stats.py`` delegates here).
* **Clopper–Pearson** (:func:`clopper_pearson_ci`) — the exact interval
  from inverting the binomial tail tests.  Conservative (coverage ≥ the
  nominal level at every ``(n, p)``), used where a guarantee-flavoured
  statement is wanted (docs/STATS.md).  Implemented via a pure-Python
  regularized incomplete beta (Lentz continued fraction + ``lgamma``) so
  there is no SciPy dependency.

The empty case is uniform by fiat: ``n == 0`` → rate ``nan`` (None in
JSON), interval ``[0, 1]``.  That is the single source of truth the
``SweepResult.success_rate`` / serve-result satellite fix routes through.
"""

from __future__ import annotations

import dataclasses
import math
from statistics import NormalDist
from typing import Any, Iterable, Mapping

__all__ = [
    "RateEstimate",
    "StreamingRate",
    "SweepEstimators",
    "clopper_pearson_ci",
    "rate_estimate",
    "round_histogram",
    "success_rate",
    "wilson_ci",
]


def success_rate(successes: int, n_trials: int) -> float:
    """The repo-wide point estimate: ``k/n``, ``nan`` when ``n == 0``.

    Single source of truth for the empty case — sweep results, surface
    cells and serve results all call this instead of dividing inline.
    """
    return successes / n_trials if n_trials else float("nan")


def _z_value(confidence: float) -> float:
    if not 0.0 < confidence < 1.0:
        raise ValueError(f"confidence must be in (0, 1), got {confidence}")
    return NormalDist().inv_cdf(0.5 + confidence / 2.0)


def wilson_ci(
    k: int, n: int, confidence: float = 0.95
) -> tuple[float, float]:
    """Wilson score interval for a binomial proportion.

    ``n == 0`` returns the vacuous ``(0.0, 1.0)``.
    """
    z = _z_value(confidence)
    return wilson_ci_z(k, n, z)


def wilson_ci_z(k: int, n: int, z: float) -> tuple[float, float]:
    """Wilson interval parameterized by the z-value directly (the form
    ``obs/stats.py`` historically exposed)."""
    if n == 0:
        return (0.0, 1.0)
    if not 0 <= k <= n:
        raise ValueError(f"need 0 <= k <= n, got k={k}, n={n}")
    p = k / n
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = (z / denom) * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n))
    return (max(0.0, center - half), min(1.0, center + half))


def _betacf(a: float, b: float, x: float) -> float:
    """Continued fraction for the incomplete beta (Lentz's method)."""
    MAXIT, EPS, FPMIN = 200, 3e-14, 1e-300
    qab, qap, qam = a + b, a + 1.0, a - 1.0
    c = 1.0
    d = 1.0 - qab * x / qap
    if abs(d) < FPMIN:
        d = FPMIN
    d = 1.0 / d
    h = d
    for m in range(1, MAXIT + 1):
        m2 = 2 * m
        aa = m * (b - m) * x / ((qam + m2) * (a + m2))
        d = 1.0 + aa * d
        if abs(d) < FPMIN:
            d = FPMIN
        c = 1.0 + aa / c
        if abs(c) < FPMIN:
            c = FPMIN
        d = 1.0 / d
        h *= d * c
        aa = -(a + m) * (qab + m) * x / ((a + m2) * (qap + m2))
        d = 1.0 + aa * d
        if abs(d) < FPMIN:
            d = FPMIN
        c = 1.0 + aa / c
        if abs(c) < FPMIN:
            c = FPMIN
        d = 1.0 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < EPS:
            return h
    raise RuntimeError(f"betacf failed to converge (a={a}, b={b}, x={x})")


def betainc_reg(a: float, b: float, x: float) -> float:
    """Regularized incomplete beta ``I_x(a, b)`` — pure Python, no SciPy.

    This is the binomial tail: ``P[X <= k] = I_{1-p}(n-k, k+1)`` for
    ``X ~ Binomial(n, p)`` (equivalently ``P[X >= k] = I_p(k, n-k+1)``).
    """
    if not 0.0 <= x <= 1.0:
        raise ValueError(f"x must be in [0, 1], got {x}")
    if x == 0.0:
        return 0.0
    if x == 1.0:
        return 1.0
    ln_front = (
        math.lgamma(a + b)
        - math.lgamma(a)
        - math.lgamma(b)
        + a * math.log(x)
        + b * math.log1p(-x)
    )
    front = math.exp(ln_front)
    # Use the continued fraction on the side where it converges fast.
    if x < (a + 1.0) / (a + b + 2.0):
        return front * _betacf(a, b, x) / a
    return 1.0 - front * _betacf(b, a, 1.0 - x) / b


def _beta_ppf(a: float, b: float, q: float) -> float:
    """Quantile of Beta(a, b) by bisection on :func:`betainc_reg`.

    Bisection (not Newton) on a monotone CDF: ~50 iterations give ~1e-15
    absolute precision, plenty for interval endpoints, and it cannot
    diverge.
    """
    lo, hi = 0.0, 1.0
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        if betainc_reg(a, b, mid) < q:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


def clopper_pearson_ci(
    k: int, n: int, confidence: float = 0.95
) -> tuple[float, float]:
    """Exact (Clopper–Pearson) binomial interval.

    Inverts the binomial tail tests: ``lo`` is the p with
    ``P[X >= k] = alpha/2`` and ``hi`` the p with ``P[X <= k] = alpha/2``,
    via the beta-quantile identities.  Coverage is ≥ ``confidence`` for
    every ``(n, p)`` — conservative by construction.  ``n == 0`` returns
    the vacuous ``(0.0, 1.0)``.
    """
    if n == 0:
        return (0.0, 1.0)
    if not 0 <= k <= n:
        raise ValueError(f"need 0 <= k <= n, got k={k}, n={n}")
    if not 0.0 < confidence < 1.0:
        raise ValueError(f"confidence must be in (0, 1), got {confidence}")
    alpha = 1.0 - confidence
    lo = 0.0 if k == 0 else _beta_ppf(k, n - k + 1, alpha / 2.0)
    hi = 1.0 if k == n else _beta_ppf(k + 1, n - k, 1.0 - alpha / 2.0)
    return (lo, hi)


_METHODS = {
    "wilson": wilson_ci,
    "clopper_pearson": clopper_pearson_ci,
}


@dataclasses.dataclass(frozen=True)
class RateEstimate:
    """A certified rate: count, trials, point estimate, CI, and how the
    CI was computed.  This is the shape manifests carry (the KI-8 lint
    rejects bare ``*_rate`` numbers that lack the ``lo``/``hi`` keys)."""

    k: int
    n: int
    rate: float  # nan when n == 0
    lo: float
    hi: float
    method: str
    confidence: float

    @property
    def width(self) -> float:
        return self.hi - self.lo

    def to_json(self) -> dict[str, Any]:
        return {
            "k": self.k,
            "n": self.n,
            # JSON has no nan; None is the uniform empty-result encoding.
            "rate": None if self.n == 0 else self.rate,
            "lo": self.lo,
            "hi": self.hi,
            "method": self.method,
            "confidence": self.confidence,
        }


def rate_estimate(
    k: int,
    n: int,
    method: str = "wilson",
    confidence: float = 0.95,
) -> RateEstimate:
    """Point estimate + CI as one :class:`RateEstimate`."""
    try:
        ci = _METHODS[method]
    except KeyError:
        raise ValueError(
            f"unknown CI method {method!r}; choose from {sorted(_METHODS)}"
        ) from None
    lo, hi = ci(k, n, confidence)
    return RateEstimate(
        k=k,
        n=n,
        rate=success_rate(k, n),
        lo=lo,
        hi=hi,
        method=method,
        confidence=confidence,
    )


class StreamingRate:
    """A binomial proportion accumulated chunk-by-chunk.

    ``observe(k, n)`` folds one chunk's counts in; :meth:`estimate` is the
    current certified rate.  Order-independent (sums of counts), so the
    adaptive allocator's reordering cannot change the final estimate.
    """

    def __init__(self, method: str = "wilson", confidence: float = 0.95):
        if method not in _METHODS:
            raise ValueError(
                f"unknown CI method {method!r}; choose from {sorted(_METHODS)}"
            )
        self.method = method
        self.confidence = confidence
        self.k = 0
        self.n = 0

    def observe(self, k: int, n: int) -> None:
        if not 0 <= k <= n:
            raise ValueError(f"need 0 <= k <= n, got k={k}, n={n}")
        self.k += int(k)
        self.n += int(n)

    def estimate(self) -> RateEstimate:
        return rate_estimate(
            self.k, self.n, method=self.method, confidence=self.confidence
        )


class SweepEstimators:
    """The host-side statistics sink for a chunked sweep: one
    :class:`StreamingRate` per tracked event class (success, overflow),
    fed from :class:`~qba_tpu.sweep.ChunkResult` aggregates.

    ``ChunkResult.overflow`` is a per-chunk *any* flag, not a count, so
    the overflow rate here is the rate of overflowing **chunks** — the
    honest statistic available from the checkpoint format.
    """

    def __init__(self, method: str = "wilson", confidence: float = 0.95):
        self.success = StreamingRate(method=method, confidence=confidence)
        self.overflow_chunks = StreamingRate(
            method=method, confidence=confidence
        )

    def observe_chunk(self, chunk) -> None:
        """Fold one ``ChunkResult`` (anything with ``.trials``,
        ``.successes``, ``.overflow``) into the running statistics."""
        self.success.observe(chunk.successes, chunk.trials)
        self.overflow_chunks.observe(1 if chunk.overflow else 0, 1)

    def observe_all(self, chunks: Iterable[Any]) -> "SweepEstimators":
        for c in chunks:
            self.observe_chunk(c)
        return self

    def summary(self) -> dict[str, Any]:
        """The manifest-ready block (every rate is a full estimate)."""
        return {
            "success_rate": self.success.estimate().to_json(),
            "overflow_chunk_rate": self.overflow_chunks.estimate().to_json(),
        }


def round_histogram(
    first_accept_rounds: Iterable[int] | Mapping[int, int],
    n_rounds: int,
    confidence: float = 0.95,
    method: str = "wilson",
) -> list[dict[str, Any]]:
    """Counter-derived round histogram with a CI per bin.

    Accepts either raw per-trial first-accept rounds or a pre-counted
    ``{round: count}`` mapping.  Each bin's frequency is a binomial
    proportion of the total trial count, so each carries the same
    certified-rate shape as everything else in a manifest.  Bins are
    emitted for ``0..n_rounds`` inclusive (the sentinel ``n_rounds``
    bucket is "never accepted").
    """
    if isinstance(first_accept_rounds, Mapping):
        counts = {int(r): int(c) for r, c in first_accept_rounds.items()}
    else:
        counts = {}
        for r in first_accept_rounds:
            counts[int(r)] = counts.get(int(r), 0) + 1
    total = sum(counts.values())
    bins = []
    for r in range(n_rounds + 1):
        k = counts.get(r, 0)
        est = rate_estimate(k, total, method=method, confidence=confidence)
        bins.append({"round": r, **est.to_json()})
    return bins
