"""Adaptive trial allocation across a cell grid.

Given a grid of cells (surface points), a shared precision target, and a
total chunk budget, the allocator decides which cell runs its next chunk.
The policy is Coz-shaped (PAPERS.md): spend the budget where it moves the
answer — cells whose confidence interval still straddles the decision
boundary — instead of uniformly.

Determinism argument (docs/STATS.md): the allocator consumes only the
per-cell running counts, which are themselves pure functions of the seed
and the set of chunks executed (``sweep.chunk_keys``); scheduling is
priority-then-index with no RNG and no timing input, so the full
(cell, chunk) execution sequence — and therefore every chunk result and
the final estimates — is reproducible given the seed and arrival order.
A resumed run replays checkpointed chunks through the same rules in
chunk order before scheduling new work, landing in an identical state.

The allocation *order* never changes the final estimates for the chunks
actually executed: each cell's chunk ``i`` draws keys from
``fold_in(key(seed), i)`` regardless of when the allocator scheduled it,
so adaptive and uniform schedules produce bit-identical per-chunk
results (tests/test_stats.py pins the differential).
"""

from __future__ import annotations

from typing import Any, Sequence

from qba_tpu.stats.sequential import StopDecision
from qba_tpu.stats.targets import Target

__all__ = ["AdaptiveAllocator"]


class _Cell:
    __slots__ = ("index", "label", "rule", "chunks_run", "decision")

    def __init__(self, index: int, label: str, target: Target):
        self.index = index
        self.label = label
        self.rule = target.make_rule()
        self.chunks_run = 0
        self.decision: StopDecision | None = None


class AdaptiveAllocator:
    """Largest-uncertainty-first chunk scheduler over a cell grid.

    Protocol: call :meth:`next_cell` to get the index of the cell that
    should run its next chunk (or ``None`` when every cell is resolved
    or the budget is spent), run that cell's next chunk, then
    :meth:`record` its counts.  The allocator folds the counts into the
    cell's stopping rule and logs a trace row.

    Priority at each step, among unresolved cells:

    1. **bootstrap** — cells with zero observed chunks, in index order
       (every cell gets one chunk before any cell gets two);
    2. **straddling** — for ``decide`` targets, cells whose running CI
       contains the threshold, widest CI first (they need the most
       evidence to resolve); for ``ci_width`` targets every unresolved
       cell straddles by definition;
    3. **undecided** — remaining unresolved cells (CI already excludes
       the threshold but the SPRT boundary has not been crossed),
       widest CI first.

    Ties break by cell index.  No randomness anywhere.
    """

    def __init__(
        self,
        labels: Sequence[str],
        target: Target,
        budget_chunks: int,
    ):
        if not labels:
            raise ValueError("allocator needs at least one cell")
        if budget_chunks < 1:
            raise ValueError(
                f"budget_chunks must be >= 1, got {budget_chunks}"
            )
        self.target = target
        self.budget_chunks = budget_chunks
        self.spent_chunks = 0
        self.cells = [
            _Cell(i, label, target) for i, label in enumerate(labels)
        ]
        #: Allocation log: one row per scheduling step, manifest-ready.
        self.trace: list[dict[str, Any]] = []

    # -- scheduling ---------------------------------------------------

    def _priority(self, cell: _Cell) -> tuple:
        """Sort key: lower sorts first."""
        if cell.chunks_run == 0:
            return (0, cell.index)
        est = cell.rule.estimate()
        width = est.width
        if self.target.kind == "decide":
            straddles = est.lo <= self.target.threshold <= est.hi
        else:
            straddles = True
        tier = 1 if straddles else 2
        # Widest interval first within the tier.
        return (tier, -width, cell.index)

    def next_cell(self) -> int | None:
        """Index of the cell to run next; ``None`` when done."""
        if self.spent_chunks >= self.budget_chunks:
            return None
        open_cells = [c for c in self.cells if c.decision is None]
        if not open_cells:
            return None
        best = min(open_cells, key=self._priority)
        tier = self._priority(best)[0]
        self.trace.append(
            {
                "step": self.spent_chunks,
                "cell": best.index,
                "label": best.label,
                "reason": ("bootstrap", "straddling", "undecided")[tier],
                "ci_width": (
                    best.rule.estimate().width if best.chunks_run else None
                ),
            }
        )
        return best.index

    def record(self, index: int, k: int, n: int) -> StopDecision | None:
        """Fold one executed chunk's counts into cell ``index``.  Returns
        the cell's stop decision if this chunk resolved it."""
        cell = self.cells[index]
        cell.rule.observe(k, n)
        cell.chunks_run += 1
        self.spent_chunks += 1
        dec = cell.rule.decision()
        if dec is not None:
            cell.decision = dec
        return dec

    def preload(self, index: int, k: int, n: int) -> StopDecision | None:
        """Replay a checkpointed chunk on resume: identical rule and
        budget accounting to :meth:`record` (the chunk really was
        executed, by a previous run) with the trace row marked
        ``resume`` instead of a scheduling reason."""
        cell = self.cells[index]
        self.trace.append(
            {
                "step": self.spent_chunks,
                "cell": index,
                "label": cell.label,
                "reason": "resume",
                "ci_width": None,
            }
        )
        return self.record(index, k, n)

    # -- results ------------------------------------------------------

    def finish(self) -> None:
        """Mark every unresolved cell ``budget_exhausted``."""
        for cell in self.cells:
            if cell.decision is None:
                cell.decision = cell.rule.exhausted()

    def decisions(self) -> list[StopDecision]:
        """Per-cell decisions (``finish()`` first to close open cells)."""
        return [
            c.decision
            if c.decision is not None
            else c.rule.exhausted()
            for c in self.cells
        ]

    def summary(self) -> dict[str, Any]:
        """Manifest-ready allocator report."""
        return {
            "target": self.target.to_json(),
            "budget_chunks": self.budget_chunks,
            "spent_chunks": self.spent_chunks,
            "cells": [
                {
                    "index": c.index,
                    "label": c.label,
                    "chunks_run": c.chunks_run,
                    "decision": (
                        c.decision.to_json()
                        if c.decision is not None
                        else None
                    ),
                }
                for c in self.cells
            ],
            "trace": list(self.trace),
        }
