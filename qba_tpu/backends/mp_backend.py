"""Multi-process message-level backend — backend=mp.

The reference's only runtime is one OS process per party exchanging
tagged MPI messages (``mpiexec -n <nParties+1> python tfg.py``,
``README.md:3-4``, ``tfg.py:310-314``).  This backend reproduces that
runtime *shape*: the coordinator (this process — the QSD/rank-0 role,
``tfg.py:103-104,351-363``) presamples the trial's randomness with the
identical key tree every other backend consumes, then spawns one OS
process per protocol party (:mod:`qba_tpu.backends.mp_party`, jax-free).
The parties self-assemble a full point-to-point Unix-socket mesh and run
the protocol for real: every packet crosses a process boundary through
the C++ PvL wire codec, rounds synchronize by message completion (BSP),
and each lieutenant decides locally before reporting back — after which
the coordinator collects decisions and prints the verdict exactly as
rank 0 does in the reference.

Decisions, accepted-sets and overflow are bit-identical to the other
three backends for the same trial key, and the event trail (reassembled
from per-party event streams by a canonical deterministic sort) is
event-for-event identical to the local backend's
(``tests/test_mp.py``).

Note: party processes start via the multiprocessing ``spawn`` method
(they must stay jax-free), so scripts calling :func:`run_trial_mp` need
the standard ``if __name__ == "__main__":`` guard.
"""

from __future__ import annotations

import multiprocessing as mp
import multiprocessing.connection as mp_conn
import os
import tempfile
import threading
import time
from typing import TYPE_CHECKING

import jax
import numpy as np

from qba_tpu.adversary import sample_attacks_round
from qba_tpu.backends.local_backend import (
    emit_host_phases,
    emit_verdict,
    presample_trial,
)
from qba_tpu.config import QBAConfig

if TYPE_CHECKING:  # pragma: no cover - typing only
    from qba_tpu.obs import EventLog


def _native_so_path() -> str:
    """Build (if needed) and return the native library path — in the
    coordinator, so party processes never compile."""
    from qba_tpu import native

    native.load()
    return native._SO


# The spawn window below mutates process-global os.environ (PYTHONPATH is
# popped so children skip sitecustomize hooks); serialize concurrent
# run_trial_mp callers so an interleaved second call cannot observe or
# clobber the saved value.
_SPAWN_ENV_LOCK = threading.Lock()


def _recv_deadline(conn, remaining: float):
    """``conn.recv()`` with a hard deadline.  ``Connection.recv`` has no
    timeout and ``poll`` only reports readability — a party wedged
    mid-send (partial multi-chunk payload written, then stuck) would
    make a bare ``recv`` block forever.  The recv runs in a daemon
    thread; on timeout the thread is abandoned (it dies with the
    process) and the caller raises."""
    out: dict = {}

    def _r():
        try:
            out["value"] = conn.recv()
        except BaseException as e:  # pragma: no cover - re-raised below
            out["error"] = e

    t = threading.Thread(target=_r, daemon=True)
    t.start()
    t.join(max(0.0, remaining))
    if t.is_alive():
        raise RuntimeError("party wedged mid-report (recv deadline)")
    if "error" in out:
        raise out["error"]
    return out["value"]


def _collect_results(procs, pipes, timeout: float) -> dict:
    """Drain every party's report pipe without ever blocking
    indefinitely: waits on the pipes AND the process sentinels with a
    shared deadline, so a party that dies without writing its pipe (hard
    kill, native-codec crash) — or wedges mid-send — raises instead of
    hanging the trial."""
    deadline = time.monotonic() + timeout
    pending = set(pipes)  # ranks still owing a report
    results = {}
    while pending:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RuntimeError(
                f"mp trial timed out after {timeout:.0f}s; ranks still "
                f"pending: {sorted(pending)}"
            )
        conns = {pipes[r]: r for r in pending}
        sentinels = {procs[r - 1].sentinel: r for r in pending}
        ready = mp_conn.wait(
            list(conns) + list(sentinels), timeout=remaining
        )
        for obj in ready:
            rank = conns.get(obj)
            if rank is None:  # a sentinel: the party process exited
                rank = sentinels[obj]
                if rank not in pending:
                    continue  # its report arrived in this same batch
                # Exit is fine iff the report was already written.
                if not pipes[rank].poll(0.1):
                    procs[rank - 1].join(timeout=1)  # reap -> exitcode
                    raise RuntimeError(
                        f"mp party rank {rank} exited (code "
                        f"{procs[rank - 1].exitcode}) without reporting"
                    )
            if rank not in pending:
                continue
            try:
                status, payload = _recv_deadline(
                    pipes[rank], deadline - time.monotonic()
                )
            except EOFError:
                procs[rank - 1].join(timeout=1)  # reap -> exitcode
                raise RuntimeError(
                    f"mp party rank {rank} closed its pipe without "
                    f"reporting (exit code {procs[rank - 1].exitcode})"
                ) from None
            if status != "ok":
                raise RuntimeError(f"mp party rank {rank} failed: {payload}")
            results[rank] = payload
            pending.discard(rank)
    return results


def run_trial_mp(
    cfg: QBAConfig,
    key: jax.Array,
    log: "EventLog | None" = None,
    trial: int = 0,
    timeout: float = 300.0,
) -> dict:
    """One protocol execution across real OS processes; returns the
    rank-0 summary dict (same shape as ``run_trial_local``).

    ``timeout`` bounds the whole collection phase: a party process that
    dies without reporting (or a wedged mesh) raises a ``RuntimeError``
    instead of blocking forever (see :func:`_collect_results`)."""
    honest, lists, v_sent, v_comm, k_rounds = presample_trial(cfg, key)
    w = cfg.w
    # Per-round effective draws, identical arrays to every other engine.
    attacks = np.stack(
        [
            np.stack(
                [
                    np.asarray(d)
                    for d in sample_attacks_round(
                        cfg, jax.random.fold_in(k_rounds, r)
                    )
                ],
                axis=-1,
            )
            for r in range(1, cfg.n_rounds + 1)
        ]
    )  # [n_rounds, n_cells, n_lieu, 3]

    so_path = _native_so_path()
    ctx = mp.get_context("spawn")
    common = dict(
        n_parties=cfg.n_parties,
        size_l=cfg.size_l,
        n_dishonest=cfg.n_dishonest,
        w=w,
        slots=cfg.slots,
        n_rounds=cfg.n_rounds,
        max_l=cfg.max_l,
        racy_defer=cfg.racy_mode == "defer",
    )

    from qba_tpu.backends import mp_party

    with tempfile.TemporaryDirectory(prefix="qba_mp_") as sock_dir:
        procs, pipes = [], {}
        try:
            # Party processes receive sys.path through the spawn
            # preparation data, so PYTHONPATH is cleared for the spawn
            # window: it only serves to inject sitecustomize hooks at
            # interpreter start (the dev box's remote-TPU plugin costs
            # ~2 s per child — a minute of pure overhead at 33
            # parties), none of which the jax-free party code uses.
            # The lock serializes the process-global env mutation.
            with _SPAWN_ENV_LOCK:
                saved_pp = os.environ.pop("PYTHONPATH", None)
                try:
                    for rank in range(1, cfg.n_parties + 1):
                        parent_conn, child_conn = ctx.Pipe(duplex=False)
                        if rank == 1:
                            params = dict(
                                common,
                                list0=[int(x) for x in lists[0]],
                                list1=[int(x) for x in lists[1]],
                                v_sent=v_sent,
                            )
                            target = mp_party.commander_main
                        else:
                            params = dict(
                                common,
                                honest=tuple(bool(h) for h in honest),
                                list=[int(x) for x in lists[rank]],
                                attacks=attacks[:, :, rank - 2, :],
                            )
                            target = mp_party.lieutenant_main
                        p = ctx.Process(
                            target=target,
                            args=(rank, sock_dir, so_path, child_conn, params),
                            daemon=True,
                        )
                        p.start()
                        child_conn.close()
                        procs.append(p)
                        pipes[rank] = parent_conn
                finally:
                    if saved_pp is not None:
                        os.environ["PYTHONPATH"] = saved_pp

            results = _collect_results(procs, pipes, timeout)
        finally:
            # Bounded cleanup: 30 s TOTAL for graceful exits (not per
            # process — a wedged 33-party mesh must not stack another
            # n_parties * 30 s of joins on top of the collection
            # timeout), then terminate whatever is left.
            stop = time.monotonic() + 30
            for p in procs:
                p.join(timeout=max(0.0, stop - time.monotonic()))
            for p in procs:
                if p.is_alive():  # pragma: no cover - hang safety
                    p.terminate()
                    p.join(timeout=5)

    decisions = [v_comm] + [
        results[r]["decision"] for r in range(2, cfg.n_parties + 1)
    ]
    vi = [
        set(results[r]["vi"]) for r in range(2, cfg.n_parties + 1)
    ]
    overflow = any(
        results[r]["overflow"] for r in range(2, cfg.n_parties + 1)
    )
    honest_parties = [bool(h) for h in honest[1:]]
    filtered = {
        d for d, h in zip(decisions, honest_parties) if h
    }
    success = len(filtered) == 1

    if log is not None:
        _emit_trail(
            cfg, log, trial, honest, lists, v_comm, v_sent, results,
            decisions, honest_parties, success,
        )

    return {
        "success": success,
        "decisions": decisions,
        "honest": honest_parties,
        "v_comm": v_comm,
        "vi": vi,
        "overflow": overflow,
    }


def _emit_trail(cfg, log, trial, honest, lists, v_comm, v_sent, results,
                decisions, honest_parties, success) -> None:
    """Reassemble the per-party event streams into the local backend's
    exact event order: host-side phases, then the (round, stage,
    receiver, sequence)-sorted protocol events, then the verdict.  The
    sort is deterministic because each party's per-(round, stage) order
    is — concurrency cannot reorder the rendered trail."""
    emit_host_phases(cfg, log, trial, honest, lists, v_comm, v_sent)
    merged = []
    for payload in results.values():
        merged.extend(payload["events"])
    merged.sort(key=lambda e: e[0])
    for _key, phase, message, fields in merged:
        log.debug(phase, message, trial=trial, **fields)
    emit_verdict(log, trial, decisions, honest_parties, success)
