"""Multi-process message-level backend — backend=mp.

The reference's only runtime is one OS process per party exchanging
tagged MPI messages (``mpiexec -n <nParties+1> python tfg.py``,
``README.md:3-4``, ``tfg.py:310-314``).  This backend reproduces that
runtime *shape*: the coordinator (this process — the QSD/rank-0 role,
``tfg.py:103-104,351-363``) presamples the trial's randomness with the
identical key tree every other backend consumes, then spawns one OS
process per protocol party (:mod:`qba_tpu.backends.mp_party`, jax-free).
The parties self-assemble a full point-to-point Unix-socket mesh and run
the protocol for real: every packet crosses a process boundary through
the C++ PvL wire codec, rounds synchronize by message completion (BSP),
and each lieutenant decides locally before reporting back — after which
the coordinator collects decisions and prints the verdict exactly as
rank 0 does in the reference.

Decisions, accepted-sets and overflow are bit-identical to the other
three backends for the same trial key, and the event trail (reassembled
from per-party event streams by a canonical deterministic sort) is
event-for-event identical to the local backend's
(``tests/test_mp.py``).

Round 4 adds batch mode: :func:`run_trials_mp` spawns the party mesh
ONCE and streams a whole batch of trials over it (the reference
amortizes nothing — one ``mpiexec`` per trial — but the differential
oracle for a Monte-Carlo framework must).  Party processes start via
``fork`` where available (see :func:`_party_context` for the measured
rationale and fork-safety analysis); scripts calling into this module
should still use the standard ``if __name__ == "__main__":`` guard for
the spawn/forkserver fallbacks.
"""

from __future__ import annotations

import multiprocessing as mp
import multiprocessing.connection as mp_conn
import os
import tempfile
import threading
import time
from typing import TYPE_CHECKING

import jax
import numpy as np

from qba_tpu.adversary import sample_attacks_round
from qba_tpu.backends.local_backend import (
    emit_host_phases,
    emit_verdict,
    presample_trial,
)
from qba_tpu.config import QBAConfig

if TYPE_CHECKING:  # pragma: no cover - typing only
    from qba_tpu.obs import EventLog


def _native_so_path() -> str:
    """Build (if needed) and return the native library path — in the
    coordinator, so party processes never compile."""
    from qba_tpu import native

    native.load()
    return native._SO


# The spawn window below mutates process-global os.environ (PYTHONPATH is
# popped so children skip sitecustomize hooks); serialize concurrent
# run_trial_mp callers so an interleaved second call cannot observe or
# clobber the saved value.
_SPAWN_ENV_LOCK = threading.Lock()

_PARTY_CTX = None


def _party_context():
    """The multiprocessing context party processes start from.

    ``fork`` on POSIX, measured orders of magnitude faster than the
    alternatives for this workload (one shared core: an 11-party mesh
    assembles in ~0.14 s forked vs ~28 s under spawn/forkserver —
    ``spawn`` re-imports the caller's typically jax-importing
    ``__main__`` in every child at ~2.5 s each, and forkserver's
    per-Connection resource-sharer fetches serialize behind the
    parent's GIL).

    Fork-safety rationale, since the parent is multi-threaded (jax):
    party children execute ONLY :mod:`qba_tpu.backends.mp_party` code —
    sockets, numpy, ctypes, struct — and never touch the inherited jax
    state; the residual risk (an allocator/runtime lock held by another
    parent thread at fork time wedging a child) is real but bounded:
    a wedged child trips the collection deadline and raises instead of
    hanging (:func:`_collect_results`), whose death detection uses
    process SENTINELS rather than pipe EOF precisely because forked
    siblings inherit each other's pipe fds.  Python 3.12's
    multi-threaded-fork DeprecationWarning is suppressed at the spawn
    site with this justification.  Falls back to forkserver (preloaded
    with the jax-free party module), then spawn."""
    global _PARTY_CTX
    if _PARTY_CTX is None:
        methods = mp.get_all_start_methods()
        if "fork" in methods:
            _PARTY_CTX = mp.get_context("fork")
        elif "forkserver" in methods:  # pragma: no cover - non-Linux
            ctx = mp.get_context("forkserver")
            try:
                ctx.set_forkserver_preload(
                    ["qba_tpu.backends.mp_party"]
                )
            except ValueError:
                pass  # someone started it first; forks still work
            _PARTY_CTX = ctx
        else:  # pragma: no cover - platform without fork entirely
            _PARTY_CTX = mp.get_context("spawn")
    return _PARTY_CTX


def _recv_deadline(conn, remaining: float):
    """``conn.recv()`` with a hard deadline.  ``Connection.recv`` has no
    timeout and ``poll`` only reports readability — a party wedged
    mid-send (partial multi-chunk payload written, then stuck) would
    make a bare ``recv`` block forever.  The recv runs in a daemon
    thread; on timeout the thread is abandoned (it dies with the
    process) and the caller raises."""
    out: dict = {}

    def _r():
        try:
            out["value"] = conn.recv()
        except BaseException as e:  # pragma: no cover - re-raised below
            out["error"] = e

    t = threading.Thread(target=_r, daemon=True)
    t.start()
    t.join(max(0.0, remaining))
    if t.is_alive():
        # Grace join before declaring a wedge: the caller may reach
        # here with remaining <= 0 for a pipe wait() just reported
        # readable (budget consumed by a sibling recv in the same
        # batch) — that recv completes in microseconds, and poisoning
        # it would cost the healthy child its graceful stop.
        t.join(0.1)
    if t.is_alive():
        # The abandoned thread is still blocked in conn.recv(); closing
        # the fd from another thread while it reads can raise unraisable
        # errors or, worse, hand a reused fd number to the blocked read.
        # Poison the connection so cleanup leaks it instead of closing
        # (the fd dies with the process; the daemon thread with it).
        conn._qba_poisoned = True
        raise RuntimeError("party wedged mid-report (recv deadline)")
    if "error" in out:
        raise out["error"]
    return out["value"]


def _send_with_deadline(pipes, messages, timeout: float) -> None:
    """Send one message per rank without ever blocking indefinitely:
    ``Connection.send`` blocks when the pipe buffer is full (a child
    wedged before its recv loop + a large work payload), which would
    hang the coordinator before the collection deadline ever runs.  All
    sends run on one daemon thread with a hard join deadline."""
    box: dict = {}

    def _s():
        rank = None
        try:
            for rank, msg in messages:
                if box.get("cancel"):  # timeout fired: stop cleanly so
                    return  # a later unblock can't race cleanup sends
                box["inflight"] = rank
                pipes[rank].send(msg)
            box.pop("inflight", None)
        except BaseException as e:  # pragma: no cover - re-raised below
            box["error"], box["rank"] = e, rank

    t = threading.Thread(target=_s, daemon=True)
    t.start()
    t.join(max(0.0, timeout))
    if t.is_alive():
        # Same hazard as _recv_deadline, send side: the abandoned
        # thread is still blocked in conn.send() on the in-flight rank.
        # Poison that connection so cleanup neither writes a second
        # interleaved frame on it nor closes the fd under the blocked
        # write (leak it; it dies with the process).  The cancel flag
        # keeps the abandoned thread from ever touching the ranks it
        # had not reached if the wedged send later unblocks — those
        # connections stay clean for the graceful stop path.
        box["cancel"] = True
        inflight = box.get("inflight")
        if inflight is not None:
            pipes[inflight]._qba_poisoned = True
        raise RuntimeError(
            f"mp work dispatch timed out after {timeout:.0f}s "
            "(party wedged before draining its work pipe?)"
        )
    if "error" in box:
        if isinstance(box["error"], (BrokenPipeError, OSError)):
            # A closed work pipe means the party process is gone —
            # surface the same diagnostic shape as the collection path.
            raise RuntimeError(
                f"mp party rank {box['rank']} closed its work pipe "
                f"without reporting (died during startup?)"
            ) from box["error"]
        raise box["error"]


def _collect_results(procs, pipes, timeout: float) -> dict:
    """Drain every party's report pipe without ever blocking
    indefinitely: waits on the pipes AND the process sentinels with a
    shared deadline, so a party that dies without writing its pipe (hard
    kill, native-codec crash) — or wedges mid-send — raises instead of
    hanging the trial."""
    deadline = time.monotonic() + timeout
    pending = set(pipes)  # ranks still owing a report
    results = {}
    while pending:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RuntimeError(
                f"mp trial timed out after {timeout:.0f}s; ranks still "
                f"pending: {sorted(pending)}"
            )
        conns = {pipes[r]: r for r in pending}
        sentinels = {procs[r - 1].sentinel: r for r in pending}
        ready = mp_conn.wait(
            list(conns) + list(sentinels), timeout=remaining
        )
        for obj in ready:
            rank = conns.get(obj)
            if rank is None:  # a sentinel: the party process exited
                rank = sentinels[obj]
                if rank not in pending:
                    continue  # its report arrived in this same batch
                # Exit is fine iff the report was already written.
                if not pipes[rank].poll(0.1):
                    procs[rank - 1].join(timeout=1)  # reap -> exitcode
                    raise RuntimeError(
                        f"mp party rank {rank} exited (code "
                        f"{procs[rank - 1].exitcode}) without reporting"
                    )
            if rank not in pending:
                continue
            try:
                status, payload = _recv_deadline(
                    pipes[rank], deadline - time.monotonic()
                )
            except EOFError:
                procs[rank - 1].join(timeout=1)  # reap -> exitcode
                raise RuntimeError(
                    f"mp party rank {rank} closed its pipe without "
                    f"reporting (exit code {procs[rank - 1].exitcode})"
                ) from None
            if status != "ok":
                raise RuntimeError(f"mp party rank {rank} failed: {payload}")
            results[rank] = payload
            pending.discard(rank)
    return results


def run_trial_mp(
    cfg: QBAConfig,
    key: jax.Array,
    log: "EventLog | None" = None,
    trial: int = 0,
    timeout: float = 300.0,
) -> dict:
    """One protocol execution across real OS processes; returns the
    rank-0 summary dict (same shape as ``run_trial_local``).

    Thin wrapper over :func:`run_trials_mp` — a one-trial batch (the
    mesh still spawns once and tears down after)."""
    return run_trials_mp(
        cfg, [key], log=log, first_trial=trial, timeout=timeout
    )[0]


def run_trials_mp(
    cfg: QBAConfig,
    keys,
    log: "EventLog | None" = None,
    first_trial: int = 0,
    timeout: float = 300.0,
    log_limit: int | None = None,
) -> list[dict]:
    """A batch of protocol executions over ONE persistent party mesh.

    The reference amortizes nothing (one ``mpiexec`` = one trial), but
    as the differential oracle for a Monte-Carlo framework this backend
    must scale past per-trial process spawns: the coordinator spawns
    ``n_parties`` processes once, streams each trial's presampled
    randomness over the per-party work pipes, and the parties run every
    trial over the same Unix-socket mesh (``qba_tpu.backends.mp_party``
    — trials are complete BSP exchanges, so the streams stay aligned).

    ``timeout`` bounds each trial's collection phase: a party that dies
    without reporting (or a wedged mesh) raises a ``RuntimeError``
    instead of blocking forever (see :func:`_collect_results`)."""
    so_path = _native_so_path()
    ctx = _party_context()
    static = dict(
        n_parties=cfg.n_parties,
        size_l=cfg.size_l,
        n_dishonest=cfg.n_dishonest,
        w=cfg.w,
        slots=cfg.slots,
        n_rounds=cfg.n_rounds,
        max_l=cfg.max_l,
        racy_defer=cfg.racy_mode == "defer",
    )

    from qba_tpu.backends import mp_party

    summaries: list[dict] = []
    with tempfile.TemporaryDirectory(prefix="qba_mp_") as sock_dir:
        procs, pipes = [], {}
        try:
            # PYTHONPATH is cleared for the spawn window — this only
            # matters for the forkserver/spawn FALLBACK start methods,
            # where a fresh interpreter would re-run sitecustomize
            # hooks (the dev box's remote-TPU plugin costs ~2 s per
            # child; children get sys.path via the spawn preparation
            # data instead).  Forked children (the default) never
            # re-exec and are unaffected.  The lock serializes the
            # process-global env mutation.
            with _SPAWN_ENV_LOCK:
                saved_pp = os.environ.pop("PYTHONPATH", None)
                try:
                    import warnings as _warnings

                    with _warnings.catch_warnings():
                        # Python >= 3.12 (DeprecationWarning) and JAX's
                        # at-fork hook (RuntimeWarning) both warn on
                        # fork from a multi-threaded parent; accepted
                        # deliberately here — see _party_context's
                        # fork-safety rationale (jax-free children,
                        # sentinel-based death detection, hard
                        # collection deadline).
                        _warnings.filterwarnings(
                            "ignore",
                            message=".*multi-threaded.*fork.*",
                            category=DeprecationWarning,
                        )
                        _warnings.filterwarnings(
                            "ignore",
                            message=".*os.fork\\(\\) is incompatible.*",
                            category=RuntimeWarning,
                        )
                        for rank in range(1, cfg.n_parties + 1):
                            parent_conn, child_conn = ctx.Pipe(duplex=True)
                            target = (
                                mp_party.commander_main
                                if rank == 1
                                else mp_party.lieutenant_main
                            )
                            p = ctx.Process(
                                target=target,
                                args=(rank, sock_dir, so_path,
                                      child_conn, dict(static)),
                                daemon=True,
                            )
                            p.start()
                            child_conn.close()
                            procs.append(p)
                            pipes[rank] = parent_conn
                finally:
                    if saved_pp is not None:
                        os.environ["PYTHONPATH"] = saved_pp

            for t_i, key in enumerate(keys):
                # log_limit bounds the trail like the CLI's
                # max_verdicts: unbounded per-packet trails flood the
                # log and skew timing on large batches.
                trail = (
                    log
                    if log_limit is None or t_i < log_limit
                    else None
                )
                summaries.append(
                    _dispatch_trial(
                        cfg, key, procs, pipes, trail,
                        first_trial + t_i, timeout,
                    )
                )
        finally:
            # Shutdown runs in the finally: after a failed trial the
            # HEALTHY parties still sit in conn.recv() awaiting more
            # work — without the stop they would burn the whole join
            # budget and end in SIGTERM.  The stop sends are
            # deadline-bounded (tiny messages, but a wedged child's
            # full buffer must not hang the cleanup), and closing the
            # parent pipe ends afterwards EOFs any child that missed
            # its stop (the party mains treat EOF as stop).
            try:
                _send_with_deadline(
                    pipes,
                    [
                        (r, ("stop",))
                        for r in pipes
                        if not getattr(pipes[r], "_qba_poisoned", False)
                    ],
                    5.0,
                )
            except Exception:  # pragma: no cover - cleanup best-effort
                pass
            for conn in pipes.values():
                if getattr(conn, "_qba_poisoned", False):
                    # A recv-deadline thread may still be blocked in
                    # conn.recv(); leak the fd (see _recv_deadline).
                    continue
                try:
                    conn.close()
                except OSError:  # pragma: no cover
                    pass
            # Bounded cleanup: 30 s TOTAL for graceful exits (not per
            # process — a wedged 33-party mesh must not stack another
            # n_parties * 30 s of joins on top of the collection
            # timeout), then terminate whatever is left.
            stop = time.monotonic() + 30
            for p in procs:
                p.join(timeout=max(0.0, stop - time.monotonic()))
            for p in procs:
                if p.is_alive():  # pragma: no cover - hang safety
                    p.terminate()
                    p.join(timeout=5)
    return summaries


def _dispatch_trial(cfg, key, procs, pipes, log, trial, timeout) -> dict:
    """Presample one trial, stream the per-party work over the pipes,
    collect and assemble the rank-0 summary."""
    honest, lists, v_sent, v_comm, k_rounds, ctx = presample_trial(cfg, key)
    # Per-round effective draws, identical arrays to every other engine.
    attacks = np.stack(
        [
            np.stack(
                [
                    np.asarray(d)
                    for d in sample_attacks_round(
                        cfg, jax.random.fold_in(k_rounds, r), r, ctx
                    )
                ],
                axis=-1,
            )
            for r in range(1, cfg.n_rounds + 1)
        ]
    )  # [n_rounds, n_cells, n_lieu, 3]

    works = []
    for rank in range(1, cfg.n_parties + 1):
        if rank == 1:
            work = dict(
                list0=[int(x) for x in lists[0]],
                list1=[int(x) for x in lists[1]],
                v_sent=v_sent,
            )
        else:
            work = dict(
                honest=tuple(bool(h) for h in honest),
                list=[int(x) for x in lists[rank]],
                attacks=attacks[:, :, rank - 2, :],
            )
        works.append((rank, ("trial", work)))
    _send_with_deadline(pipes, works, timeout)

    results = _collect_results(procs, pipes, timeout)

    decisions = [v_comm] + [
        results[r]["decision"] for r in range(2, cfg.n_parties + 1)
    ]
    vi = [
        set(results[r]["vi"]) for r in range(2, cfg.n_parties + 1)
    ]
    overflow = any(
        results[r]["overflow"] for r in range(2, cfg.n_parties + 1)
    )
    honest_parties = [bool(h) for h in honest[1:]]
    filtered = {
        d for d, h in zip(decisions, honest_parties) if h
    }
    success = len(filtered) == 1

    if log is not None:
        _emit_trail(
            cfg, log, trial, honest, lists, v_comm, v_sent, results,
            decisions, honest_parties, success,
        )

    return {
        "success": success,
        "decisions": decisions,
        "honest": honest_parties,
        "v_comm": v_comm,
        "vi": vi,
        "overflow": overflow,
    }


def _emit_trail(cfg, log, trial, honest, lists, v_comm, v_sent, results,
                decisions, honest_parties, success) -> None:
    """Reassemble the per-party event streams into the local backend's
    exact event order: host-side phases, then the (round, stage,
    receiver, sequence)-sorted protocol events, then the verdict.  The
    sort is deterministic because each party's per-(round, stage) order
    is — concurrency cannot reorder the rendered trail."""
    emit_host_phases(cfg, log, trial, honest, lists, v_comm, v_sent)
    merged = []
    for payload in results.values():
        merged.extend(payload["events"])
    merged.sort(key=lambda e: e[0])
    for _key, phase, message, fields in merged:
        log.debug(phase, message, trial=trial, **fields)
    emit_verdict(log, trial, decisions, honest_parties, success)
