"""Execution backends (SURVEY §7.5 — the plugin seam).

The reference's backend boundary is the MPI rank: one OS process per party
(``tfg.py:310-314``).  Here:

* ``jax`` — the production path: trials ``vmap``-batched and jitted, party
  and position axes vectorized, shardable over a TPU mesh
  (:mod:`qba_tpu.parallel`).
* ``local`` — a message-level pure-Python reference path preserving the
  per-party send/receive structure (sets of tuples, per-party mailboxes)
  for differential testing and CPU baselining.  It consumes the *same*
  keyed randomness as the jax engine, so per-trial outcomes must match
  exactly — the two independent implementations check each other.
* ``native`` — the C++ host runtime (:mod:`qba_tpu.native`): the same
  message-level semantics with every packet passing through the PvL wire
  codec, closing a three-way differential triangle with the other two.
  Imported lazily (needs the native toolchain at first use).
* ``mp`` — the reference's actual runtime shape: one OS process per
  party over a Unix-socket mesh, every packet through the C++ PvL codec
  across a real process boundary (:mod:`qba_tpu.backends.mp_backend`;
  imported lazily).  Fourth corner of the differential.
"""

# Lazy exports: the mp backend's party processes import
# qba_tpu.backends.mp_party (jax-free) through this package; an eager
# jax_backend import here cost every spawned party ~2-3 s of jax import
# it never uses (33 parties = a minute of pure spawn overhead).
_EXPORTS = {
    "MonteCarloResult": ("qba_tpu.backends.jax_backend", "MonteCarloResult"),
    "run_trials": ("qba_tpu.backends.jax_backend", "run_trials"),
    "run_trial_local": ("qba_tpu.backends.local_backend", "run_trial_local"),
    "run_trial_mp": ("qba_tpu.backends.mp_backend", "run_trial_mp"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        module, attr = _EXPORTS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
