"""Execution backends (SURVEY §7.5 — the plugin seam).

The reference's backend boundary is the MPI rank: one OS process per party
(``tfg.py:310-314``).  Here:

* ``jax`` — the production path: trials ``vmap``-batched and jitted, party
  and position axes vectorized, shardable over a TPU mesh
  (:mod:`qba_tpu.parallel`).
* ``local`` — a message-level pure-Python reference path preserving the
  per-party send/receive structure (sets of tuples, per-party mailboxes)
  for differential testing and CPU baselining.  It consumes the *same*
  keyed randomness as the jax engine, so per-trial outcomes must match
  exactly — the two independent implementations check each other.
* ``native`` — the C++ host runtime (:mod:`qba_tpu.native`): the same
  message-level semantics with every packet passing through the PvL wire
  codec, closing a three-way differential triangle with the other two.
  Imported lazily (needs the native toolchain at first use).
* ``mp`` — the reference's actual runtime shape: one OS process per
  party over a Unix-socket mesh, every packet through the C++ PvL codec
  across a real process boundary (:mod:`qba_tpu.backends.mp_backend`;
  imported lazily).  Fourth corner of the differential.
"""

from qba_tpu.backends.jax_backend import MonteCarloResult, run_trials
from qba_tpu.backends.local_backend import run_trial_local

__all__ = ["MonteCarloResult", "run_trials", "run_trial_local"]
