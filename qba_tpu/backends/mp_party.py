"""Per-party process logic for the multi-process backend.

This module is imported by freshly spawned OS processes (one per
protocol party) and must stay **jax-free**: a child re-imports its
target module under the ``spawn`` start method, and dragging the JAX
runtime (and a possibly remote TPU backend) into every party process
would be both slow and wrong — the reference's per-rank processes run
plain host code over MPI (``tfg.py:310-314``).

Transport: every party listens on a Unix-domain socket under a run-
private directory and dials its lower-ranked peers (rank sent as a
4-byte hello), building the same full point-to-point mesh ``mpiexec``
gives the reference.  Every packet crosses a real process boundary
through the C++ PvL wire codec (``qba_native.cc`` ``qba_encode_pvl`` /
``qba_decode_pvl`` — the ``send_pvl``/``recv_pvl`` format of
``tfg.py:199-263``), length-framed; the wire format is load-bearing, not
decorative.

Synchronization is message-driven BSP, like the reference's
barrier-separated rounds (``tfg.py:335,348``) but race-free by
construction: each lieutenant sends exactly one batch per peer per
round and blocks reading exactly one batch per peer per round, so a
round cannot start before the previous one's traffic is drained.  Sends
run on a helper thread so the all-send-then-all-receive pattern cannot
deadlock on full socket buffers.

Protocol semantics mirror the message-level local backend exactly
(``lieu_receive``, ``tfg.py:289-300``; delivery-time corruption from the
presampled per-cell draws; ``racy_mode`` loss/defer) — the differential
tests pin decision- and trail-equality across all four backends.
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import threading
import time

import numpy as np

_i32p = ctypes.POINTER(ctypes.c_int32)

# Attack-edit bits (qba_tpu.adversary; redeclared to stay jax-free —
# tests/test_event_trail.py asserts the table matches EFFECT_NAMES).
_DROP, _FORGE, _CLEAR_P, _CLEAR_L, _FORGE_P = 1, 2, 4, 8, 16
_EFFECTS = ((_DROP, "drop"), (_FORGE, "corrupt-v"),
            (_CLEAR_P, "clear-P"), (_CLEAR_L, "clear-L"),
            (_FORGE_P, "forge-P"))


def _effect_names(bits: int) -> str:
    names = [n for b, n in _EFFECTS if bits & b]
    return "+".join(names) if names else "none"


class _Codec:
    """ctypes bindings to the already-built native library (the parent
    guarantees the .so exists; children never run the build)."""

    def __init__(self, so_path: str, size_l: int, max_l: int):
        lib = ctypes.CDLL(so_path)
        lib.qba_encode_pvl.restype = ctypes.c_int
        lib.qba_encode_pvl.argtypes = [
            _i32p, ctypes.c_int, ctypes.c_int32, _i32p, _i32p,
            ctypes.c_int, ctypes.c_int, _i32p, ctypes.c_int,
        ]
        lib.qba_decode_pvl.restype = ctypes.c_int
        lib.qba_decode_pvl.argtypes = [
            _i32p, ctypes.c_int, _i32p, ctypes.c_int, _i32p, _i32p,
            ctypes.c_int, ctypes.c_int, _i32p,
        ]
        self.lib = lib
        self.size_l = size_l
        self.nt_cap = max_l + 1
        self.cap = 3 + size_l + self.nt_cap * (1 + size_l)

    def encode(self, p: set, v: int, L: set) -> bytes:
        p_a = np.asarray(sorted(p), dtype=np.int32)
        tuples = np.zeros((self.nt_cap, self.size_l), dtype=np.int32)
        lens = np.zeros((self.nt_cap,), dtype=np.int32)
        for t_i, t in enumerate(L):
            lens[t_i] = len(t)
            tuples[t_i, : len(t)] = t
        out = np.zeros((self.cap,), dtype=np.int32)
        n = self.lib.qba_encode_pvl(
            p_a.ctypes.data_as(_i32p), len(p_a), v,
            tuples.ctypes.data_as(_i32p), lens.ctypes.data_as(_i32p),
            len(L), self.size_l, out.ctypes.data_as(_i32p), self.cap,
        )
        if n < 0:
            raise RuntimeError("PvL encode overflow")
        return out[:n].tobytes()

    def decode(self, data: bytes):
        buf = np.frombuffer(data, dtype=np.int32)
        p_out = np.zeros((self.size_l,), dtype=np.int32)
        tuples = np.zeros((self.nt_cap, self.size_l), dtype=np.int32)
        lens = np.zeros((self.nt_cap,), dtype=np.int32)
        header = np.zeros((3,), dtype=np.int32)
        used = self.lib.qba_decode_pvl(
            buf.ctypes.data_as(_i32p), len(buf),
            p_out.ctypes.data_as(_i32p), self.size_l,
            tuples.ctypes.data_as(_i32p), lens.ctypes.data_as(_i32p),
            self.nt_cap, self.size_l, header.ctypes.data_as(_i32p),
        )
        if used < 0:
            raise RuntimeError("malformed PvL wire buffer")
        n_p, v, n_t = (int(x) for x in header)
        p = {int(x) for x in p_out[:n_p]}
        L = {
            tuple(int(x) for x in tuples[t_i, : lens[t_i]])
            for t_i in range(n_t)
        }
        return p, v, L


def _consistent(v: int, L: set, w: int) -> bool:
    """The reference predicate (``tfg.py:87-98``) over sets of tuples —
    same shape as the local backend's (independent implementations,
    differentially pinned)."""
    if not L:
        return True
    lens = {len(t) for t in L}
    if len(lens) != 1:
        return False
    if not all(0 <= x <= w and x != v for t in L for x in t):
        return False
    n = next(iter(lens))
    return all(
        all(a[k] != b[k] for k in range(n))
        for a in L for b in L if a < b
    )


# ---------------------------------------------------------------------------
# Socket plumbing.

def _sock_path(sock_dir: str, rank: int) -> str:
    return os.path.join(sock_dir, f"party{rank}.sock")


def _send_msg(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, n)


def _build_mesh(rank: int, peers: list[int], sock_dir: str,
                timeout: float = 30.0) -> dict[int, socket.socket]:
    """Full p2p mesh: listen on own path; dial every lower-ranked peer
    (hello = our rank), accept from every higher-ranked one."""
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(_sock_path(sock_dir, rank))
    lower = [p for p in peers if p < rank]
    higher = [p for p in peers if p > rank]
    listener.listen(len(higher) + 1)
    conns: dict[int, socket.socket] = {}
    for p in lower:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + timeout
        while True:
            try:
                s.connect(_sock_path(sock_dir, p))
                break
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.01)
        _send_msg(s, struct.pack("<I", rank))
        conns[p] = s
    for _ in higher:
        s, _addr = listener.accept()
        (r,) = struct.unpack("<I", _recv_msg(s))
        conns[r] = s
    listener.close()
    return conns


# ---------------------------------------------------------------------------
# Party mains (Process targets — spawn-safe, jax-free).
#
# Batch mode (round 4, VERDICT r3 item 4): a party process builds its
# socket mesh ONCE and then serves a stream of trials — the coordinator
# sends ("trial", per-trial params) over the duplex work pipe, the party
# runs the protocol over the persistent mesh and replies ("ok", result),
# until ("stop",).  This amortizes the n_parties+1 process spawns
# (~0.1-0.5 s each) across a whole Monte-Carlo batch, matching the
# runtime shape of the reference's single mpiexec launch
# (``tfg.py:310-314``) rather than one launch per trial.  Stream
# alignment needs no per-trial framing: every trial is a complete BSP
# exchange (each party reads exactly the messages the trial defines), so
# consecutive trials cannot interleave on the sockets.

def commander_main(rank, sock_dir, so_path, conn, params):
    """Rank 1 (``tfg.py:166-184``): per trial, compute each
    lieutenant's packet from the recovered Q-correlated set and send it
    over the wire; the equivocation split is already folded into
    ``v_sent``."""
    try:
        size_l = params["size_l"]
        codec = _Codec(so_path, size_l, params["max_l"])
        lieu_ranks = list(range(2, params["n_parties"] + 1))
        conns = _build_mesh(rank, lieu_ranks, sock_dir)
        while True:
            try:
                msg = conn.recv()
            except EOFError:  # coordinator closed the pipe = stop
                break
            if msg[0] != "trial":
                break
            work = msg[1]
            row0, row1 = work["list0"], work["list1"]
            isq = {k for k in range(size_l) if row0[k] != row1[k]}
            events = []
            for i, r in enumerate(lieu_ranks):
                v = work["v_sent"][i]
                p = {k for k in isq if row1[k] == v}
                events.append(
                    ((0, 0, i, 0), "step2", "send",
                     dict(sender=1, dest=r, v=v, p_size=len(p), l_size=0))
                )
                _send_msg(conns[r], codec.encode(p, v, set()))
            conn.send(("ok", {"events": events}))
        for s in conns.values():
            s.close()
    except Exception as e:  # pragma: no cover - surfaced by the parent
        conn.send(("error", f"{type(e).__name__}: {e}"))
    finally:
        conn.close()


def lieutenant_main(rank, sock_dir, so_path, conn, params):
    """One lieutenant (rank 2..n_parties): per trial, step 3a on the
    commander's wire packet, then the synchronous voting rounds against
    every peer (``tfg.py:185-300,337-348``), decision at the end."""
    try:
        codec = _Codec(so_path, params["size_l"], params["max_l"])
        peers = [
            r for r in range(1, params["n_parties"] + 1) if r != rank
        ]
        conns = _build_mesh(rank, peers, sock_dir)
        while True:
            try:
                msg = conn.recv()
            except EOFError:  # coordinator closed the pipe = stop
                break
            if msg[0] != "trial":
                break
            conn.send(_run_lieutenant(rank, codec, conns, params, msg[1]))
        for s in conns.values():
            s.close()
    except Exception as e:  # pragma: no cover - surfaced by the parent
        conn.send(("error", f"{type(e).__name__}: {e}"))
    finally:
        conn.close()


def _run_lieutenant(rank, codec, conns, params, work):
    n_parties = params["n_parties"]
    w, slots = params["w"], params["slots"]
    n_dis, n_rounds = params["n_dishonest"], params["n_rounds"]
    racy_defer = params["racy_defer"]
    honest = work["honest"]  # rank-indexed tuple[bool]
    li = work["list"]  # own particle list (ints)
    attacks = np.asarray(work["attacks"])  # [n_rounds, n_cells, 3]
    me = rank - 2  # lieutenant index
    peers = [r for r in range(1, n_parties + 1) if r != rank]
    lieu_peers = [r for r in peers if r >= 2]

    events: list = []
    vi: set = set()
    overflow = False

    def emit(key, phase, message, **fields):
        events.append((key, phase, message, fields))

    # Step 3a (tfg.py:185-196): the commander's packet over the wire.
    p0, v0, L0 = codec.decode(_recv_msg(conns[1]))
    ell = set(L0)
    ell.add(tuple(li[j] for j in sorted(p0)))
    ok = _consistent(v0, ell, w)
    emit((0, 0, me, 1), "step3a", "receive", rank=rank, v=v0,
         accepted=ok, reason="accepted" if ok else "inconsistent")
    out: list = [(p0, v0, ell)] if ok else []
    if ok:
        vi.add(v0)

    deferred: list = []  # (sender_rank, p2, v2, ell2)
    for rnd in range(1, n_rounds + 1):
        # Ship the previous stage's acceptances to every lieutenant peer
        # from a helper thread (all parties send before reading; the
        # thread keeps full socket buffers from deadlocking the mesh).
        batch = [codec.encode(p, v, ell) for p, v, ell in out]

        def ship():
            payload = struct.pack("<I", len(batch)) + b"".join(
                struct.pack("<I", len(b)) + b for b in batch
            )
            for r in lieu_peers:
                _send_msg(conns[r], payload)

        shipper = threading.Thread(target=ship)
        shipper.start()

        out = []
        next_deferred: list = []
        seq = [0]

        def lieu_receive(sender_rank, p2, v2, ell2, was_deferred=False):
            """tfg.py:289-300 for one delivered packet."""
            nonlocal overflow
            ell2 = set(ell2)
            ell2.add(tuple(li[j] for j in sorted(p2)))
            if not _consistent(v2, ell2, w):
                reason = "inconsistent"
            elif v2 in vi:
                reason = "duplicate-v"
            elif len(ell2) != rnd + 1:
                reason = "wrong-evidence-len"
            else:
                reason = "accepted"
            fields = dict(
                round=rnd, sender=sender_rank, recv=rank, v=v2,
                accepted=reason == "accepted", reason=reason,
            )
            if was_deferred:
                fields["deferred"] = True
            stage = 0 if was_deferred else 1
            emit((rnd, stage, me, seq[0]), "round", "receive", **fields)
            seq[0] += 1
            if reason == "accepted":
                vi.add(v2)
                if rnd <= n_dis:
                    if len(out) < slots:
                        out.append((p2, v2, ell2))
                        emit((rnd, 1, me, seq[0]), "round", "send",
                             round=rnd, sender=rank, v=v2,
                             p_size=len(p2), l_size=len(ell2),
                             broadcast=True)
                        seq[0] += 1
                    else:
                        overflow = True

        # Deferred arrivals drain first (racy_mode="defer", D1).
        for sender_rank, p2, v2, ell2 in deferred:
            lieu_receive(sender_rank, p2, v2, ell2, was_deferred=True)

        # One batch from every lieutenant peer, in sender rank order
        # (D5 packet ordering).
        for r in sorted(lieu_peers):
            data = _recv_msg(conns[r])
            off = 0
            (count,) = struct.unpack_from("<I", data, off)
            off += 4
            sender = r - 2
            for slot in range(count):
                (blen,) = struct.unpack_from("<I", data, off)
                off += 4
                wire = data[off : off + blen]
                off += blen
                if slot >= slots:
                    continue
                p2, v2, ell2 = codec.decode(wire)
                cell = sender * slots + slot
                bits, rand_v, late = (
                    int(x) for x in attacks[rnd - 1, cell]
                )
                if late and not racy_defer:
                    emit((rnd, 1, me, seq[0]), "round", "late loss",
                         round=rnd, sender=r, recv=rank)
                    seq[0] += 1
                    continue
                if not honest[r]:  # tfg.py:271-284
                    emit((rnd, 1, me, seq[0]), "round", "attack",
                         round=rnd, sender=r, recv=rank,
                         action=_effect_names(bits))
                    seq[0] += 1
                    if bits & _DROP:
                        continue
                    if bits & _FORGE:
                        v2 = rand_v
                    if bits & _CLEAR_P:
                        p2 = set()
                    if bits & _CLEAR_L:
                        ell2 = set()
                    if bits & _FORGE_P:
                        # Worst-case P forgery (strategy="split"):
                        # fabricated all-positions mask, wins over clear.
                        p2 = set(range(params["size_l"]))
                if late:  # racy_mode="defer": next round's drain
                    emit((rnd, 1, me, seq[0]), "round", "late defer",
                         round=rnd, sender=r, recv=rank)
                    seq[0] += 1
                    next_deferred.append((r, p2, v2, ell2))
                    continue
                lieu_receive(r, p2, v2, ell2)

        emit((rnd, 2, me, 0), "round", "vi", round=rnd, rank=rank,
             vi=sorted(vi))
        shipper.join()
        deferred = next_deferred

    # Connections stay open — the mesh is persistent across the batch.
    # Decision (tfg.py:303-306; empty-Vi sentinel = w, DIVERGENCES D2).
    decision = min(vi) if vi else w
    return (
        "ok",
        {
            "decision": decision,
            "vi": sorted(vi),
            "overflow": overflow,
            "events": events,
        },
    )

