"""C++ message-level backend — backend=native.

A third, independent implementation of the protocol (after the JAX array
engine and the pure-Python local backend): the C++ host runtime in
:mod:`qba_tpu.native` executes a full trial over per-party mailboxes, with
every packet passing through the PvL wire codec — the in-process analog of
the reference's tagged-MPI transport (``tfg.py:199-263``).

Randomness is pre-sampled here with the *identical* key tree the other
two backends consume (dishonesty, lists, orders, per-(round, receiver,
cell) attack + late-loss triples), so for any config and trial key all
three implementations must produce identical decisions and verdicts —
``tests/test_native.py`` enforces the three-way match.
"""

from __future__ import annotations

import ctypes
import functools

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.adversary import (
    assign_dishonest,
    commander_orders,
    sample_attacks_round,
)
from qba_tpu.config import QBAConfig
from qba_tpu.native import load
from qba_tpu.qsim import generate_lists_for

_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _i32(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.int32)
    return a, a.ctypes.data_as(_i32p)


def _u8(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.uint8)
    return a, a.ctypes.data_as(_u8p)


@functools.partial(jax.jit, static_argnums=0)
def _attack_triples(cfg: QBAConfig, k_rounds: jax.Array) -> jax.Array:
    """int32[n_rounds, n_lieu, n_lieu*slots, 3] — the (attack, rand_v,
    late) effective draws for every delivery cell: the same batched
    per-round arrays of :func:`sample_attacks_round` the other two
    backends consume (bit-exact three-way contract, attack scope folded
    in).  ``late`` is the racy-delivery loss flag (docs/DIVERGENCES.md
    D1), all-zero under ``delivery="sync"``."""
    def one_round(r):
        draws = sample_attacks_round(cfg, jax.random.fold_in(k_rounds, r))
        # Draws are packet-major [n_pk, n_lieu]; the C ABI keeps the
        # (receiver, cell) order, so transpose host-side (cheap, CPU jit).
        return jnp.stack([d.astype(jnp.int32).T for d in draws], axis=-1)

    return jax.vmap(one_round)(jnp.arange(1, cfg.n_rounds + 1))


def run_trial_native(cfg: QBAConfig, key: jax.Array) -> dict:
    """One protocol execution in the C++ runtime; returns the rank-0
    summary dict (same shape as
    :func:`qba_tpu.backends.local_backend.run_trial_local`).

    Delegates to :func:`run_trials_native` with a singleton batch so the
    per-trial key-tree derivation exists exactly once."""
    res = run_trials_native(cfg, key[None], n_threads=1)
    w, n_lieu = cfg.w, cfg.n_lieutenants
    return {
        "success": bool(res["success"][0]),
        "decisions": [int(x) for x in res["decisions"][0]],
        "honest": [bool(h) for h in res["honest"][0]],
        "v_comm": int(res["v_comm"][0]),
        "vi": [
            {int(x) for x in range(w) if res["vi"][0, i, x]}
            for i in range(n_lieu)
        ],
        "overflow": bool(res["overflow"][0]),
    }


@functools.partial(jax.jit, static_argnums=0)
def _batch_presample(cfg: QBAConfig, keys: jax.Array):
    """All trials' pre-sampled randomness in one jitted batch (the same
    per-trial key tree, vmapped)."""
    def one(key):
        k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
        honest = assign_dishonest(cfg, k_dis)
        lists = generate_lists_for(cfg, k_lists)[0]
        v_sent, v_comm = commander_orders(cfg, k_comm, honest[1])
        return honest, lists, v_sent, v_comm, _attack_triples(cfg, k_rounds)

    return jax.vmap(one)(keys)


def run_trials_native(
    cfg: QBAConfig, keys: jax.Array | None = None, n_threads: int = 0
) -> dict:
    """Monte-Carlo batch on the C++ runtime's threaded executor.

    Randomness is pre-sampled in one jitted batch (identical key tree to
    the other backends), then ``qba_run_trials`` fans the trials out over
    a host thread pool (``n_threads <= 0`` = hardware concurrency).
    Returns a dict of stacked arrays: ``success [n]``, ``decisions
    [n, n_parties]``, ``honest [n, n_parties]``, ``v_comm [n]``, ``vi
    [n, n_lieutenants, w]``, ``overflow [n]``, ``success_rate``.
    """
    from qba_tpu.backends.jax_backend import trial_keys

    lib = load()
    if keys is None:
        keys = trial_keys(cfg)
    n = keys.shape[0]
    honest, lists, v_sent, v_comm, attacks = (
        np.asarray(x) for x in _batch_presample(cfg, keys)
    )

    n_lieu, w = cfg.n_lieutenants, cfg.w
    honest_a, honest_p = _u8(honest)
    lists_a, lists_p = _i32(lists)
    vs_a, vs_p = _i32(v_sent)
    vc_a, vc_p = _i32(v_comm)
    at_a, at_p = _i32(attacks)
    decisions = np.zeros((n, cfg.n_parties), dtype=np.int32)
    vi = np.zeros((n, n_lieu, w), dtype=np.uint8)
    flags = np.zeros((n, 2), dtype=np.int32)

    rc = lib.qba_run_trials(
        n,
        n_threads,
        cfg.n_parties,
        cfg.size_l,
        cfg.n_dishonest,
        w,
        cfg.slots,
        honest_p,
        lists_p,
        vs_p,
        vc_p,
        at_p,
        decisions.ctypes.data_as(_i32p),
        vi.ctypes.data_as(_u8p),
        flags.ctypes.data_as(_i32p),
    )
    if rc != 0:
        raise RuntimeError(f"qba_run_trials failed with rc={rc}")

    return {
        "success": flags[:, 0].astype(bool),
        "decisions": decisions,
        "honest": honest_a[:, 1:].astype(bool),
        "v_comm": vc_a,
        "vi": vi.astype(bool),
        "overflow": flags[:, 1].astype(bool),
        "success_rate": float(flags[:, 0].mean()),
    }
