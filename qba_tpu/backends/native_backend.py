"""C++ message-level backend — backend=native.

A third, independent implementation of the protocol (after the JAX array
engine and the pure-Python local backend): the C++ host runtime in
:mod:`qba_tpu.native` executes a full trial over per-party mailboxes, with
every packet passing through the PvL wire codec — the in-process analog of
the reference's tagged-MPI transport (``tfg.py:199-263``).

Randomness is pre-sampled here with the *identical* key tree the other
two backends consume (dishonesty, lists, orders, per-(round, receiver,
cell) attack + late-loss quads), so for any config and trial key all
three implementations must produce identical decisions and verdicts —
``tests/test_native.py`` enforces the three-way match.
"""

from __future__ import annotations

import ctypes
import functools

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.adversary import (
    assign_dishonest,
    commander_orders,
    sample_attacks_round,
)
from qba_tpu.config import QBAConfig
from qba_tpu.native import load
from qba_tpu.qsim import generate_lists_for

_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _i32(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.int32)
    return a, a.ctypes.data_as(_i32p)


def _u8(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.uint8)
    return a, a.ctypes.data_as(_u8p)


@functools.partial(jax.jit, static_argnums=0)
def _attack_quads(cfg: QBAConfig, k_rounds: jax.Array) -> jax.Array:
    """int32[n_rounds, n_lieu, n_lieu*slots, 4] — the (action, coin,
    rand_v, late) draws for every delivery cell: the same batched
    per-round arrays of :func:`sample_attacks_round` the other two
    backends consume (bit-exact three-way contract).  ``late`` is the
    racy-delivery loss flag (docs/DIVERGENCES.md D1), all-zero under
    ``delivery="sync"``."""
    def one_round(r):
        draws = sample_attacks_round(cfg, jax.random.fold_in(k_rounds, r))
        return jnp.stack([d.astype(jnp.int32) for d in draws], axis=-1)

    return jax.vmap(one_round)(jnp.arange(1, cfg.n_rounds + 1))


def run_trial_native(cfg: QBAConfig, key: jax.Array) -> dict:
    """One protocol execution in the C++ runtime; returns the rank-0
    summary dict (same shape as
    :func:`qba_tpu.backends.local_backend.run_trial_local`)."""
    lib = load()
    k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)

    honest = np.asarray(assign_dishonest(cfg, k_dis))
    lists = np.asarray(generate_lists_for(cfg, k_lists)[0])
    v_sent_arr, v_comm = commander_orders(
        cfg, k_comm, jnp.asarray(bool(honest[1]))
    )
    attacks = np.asarray(_attack_quads(cfg, k_rounds))

    n_lieu, w = cfg.n_lieutenants, cfg.w
    honest_a, honest_p = _u8(honest)
    lists_a, lists_p = _i32(lists)
    vs_a, vs_p = _i32(np.asarray(v_sent_arr))
    at_a, at_p = _i32(attacks)
    decisions = np.zeros(cfg.n_parties, dtype=np.int32)
    vi = np.zeros((n_lieu, w), dtype=np.uint8)
    flags = np.zeros(2, dtype=np.int32)
    _, dec_p = decisions, decisions.ctypes.data_as(_i32p)
    _, vi_p = vi, vi.ctypes.data_as(_u8p)
    _, fl_p = flags, flags.ctypes.data_as(_i32p)

    rc = lib.qba_run_trial(
        cfg.n_parties,
        cfg.size_l,
        cfg.n_dishonest,
        w,
        cfg.slots,
        honest_p,
        lists_p,
        vs_p,
        int(v_comm),
        at_p,
        dec_p,
        vi_p,
        fl_p,
    )
    if rc != 0:
        raise RuntimeError(f"qba_run_trial failed with rc={rc}")

    return {
        "success": bool(flags[0]),
        "decisions": [int(x) for x in decisions],
        "honest": [bool(h) for h in honest[1:]],
        "v_comm": int(v_comm),
        "vi": [
            {int(x) for x in range(w) if vi[i, x]} for i in range(n_lieu)
        ],
        "overflow": bool(flags[1]),
    }
