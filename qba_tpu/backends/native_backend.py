"""C++ message-level backend — backend=native.

A third, independent implementation of the protocol (after the JAX array
engine and the pure-Python local backend): the C++ host runtime in
:mod:`qba_tpu.native` executes a full trial over per-party mailboxes, with
every packet passing through the PvL wire codec — the in-process analog of
the reference's tagged-MPI transport (``tfg.py:199-263``).

Randomness is pre-sampled here with the *identical* key tree the other
two backends consume (dishonesty, lists, orders, per-(round, receiver,
cell) attack + late-loss triples), so for any config and trial key all
three implementations must produce identical decisions and verdicts —
``tests/test_native.py`` enforces the three-way match.
"""

from __future__ import annotations

import ctypes
import functools

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.adversary import (
    adversary_ctx,
    assign_dishonest,
    commander_orders,
    effect_names,
    sample_attacks_round,
)
from qba_tpu.config import QBAConfig
from qba_tpu.native import load
from qba_tpu.qsim import generate_lists_for

_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _i32(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.int32)
    return a, a.ctypes.data_as(_i32p)


def _u8(a: np.ndarray):
    a = np.ascontiguousarray(a, dtype=np.uint8)
    return a, a.ctypes.data_as(_u8p)


@functools.partial(jax.jit, static_argnums=0)
def _attack_triples(cfg: QBAConfig, k_rounds: jax.Array, ctx=None) -> jax.Array:
    """int32[n_rounds, n_lieu, n_lieu*slots, 3] — the (attack, rand_v,
    late) effective draws for every delivery cell: the same batched
    per-round arrays of :func:`sample_attacks_round` the other two
    backends consume (bit-exact three-way contract, attack scope and
    strategy folded in — the C engine only ever sees effective edits).
    ``late`` is the racy-delivery loss flag (docs/DIVERGENCES.md
    D1), all-zero under ``delivery="sync"``."""
    def one_round(r):
        draws = sample_attacks_round(
            cfg, jax.random.fold_in(k_rounds, r), r, ctx
        )
        # Draws are packet-major [n_pk, n_lieu]; the C ABI keeps the
        # (receiver, cell) order, so transpose host-side (cheap, CPU jit).
        return jnp.stack([d.astype(jnp.int32).T for d in draws], axis=-1)

    return jax.vmap(one_round)(jnp.arange(1, cfg.n_rounds + 1))


# C trace record layout (see qba_native.cc qba_run_trial docs): 7-int32
# records {kind, round, sender_rank, recv_rank, v, a, b}.
_TRACE_REC = 7
_REASONS = ("accepted", "inconsistent", "duplicate-v", "wrong-evidence-len")


def _emit_trace(cfg: QBAConfig, log, trial: int, recs: np.ndarray) -> None:
    """Render the C engine's trace records as the same event grammar the
    local backend emits (tests/test_native.py pins the match).

    Kind 7 opens a per-(round, rank) accepted-set snapshot expecting
    ``a`` kind-8 value records; a truncated trace can cut the value list
    short, in which case the partial snapshot is dropped rather than
    rendered wrong."""
    pending = None  # (round, rank, expected, values)

    def flush_pending():
        nonlocal pending
        if pending is None:
            return
        rnd, rank, expect, vals = pending
        pending = None
        if len(vals) == expect:
            log.debug("round", "vi", trial=trial, round=rnd, rank=rank,
                      vi=sorted(vals))

    for kind, rnd, sender, recv, v, a, b in recs.tolist():
        if kind == 8:
            if pending is not None:
                pending[3].append(v)
                if len(pending[3]) == pending[2]:
                    flush_pending()
            continue
        flush_pending()
        if kind == 7:  # per-round accepted-set snapshot header
            pending = (rnd, sender, a, [])
            if a == 0:
                flush_pending()
            continue
        if kind == 1:  # step2 send (tfg.py:203)
            log.debug("step2", "send", trial=trial, sender=sender,
                      dest=recv, v=v, p_size=a, l_size=0)
        elif kind == 2:  # step3a receive (tfg.py:190)
            log.debug("step3a", "receive", trial=trial, rank=recv, v=v,
                      accepted=bool(a), reason=_REASONS[b])
        elif kind == 3:  # racy late loss (DIVERGENCES D1)
            log.debug("round", "late loss", trial=trial, round=rnd,
                      sender=sender, recv=recv)
        elif kind == 4:  # attack action (tfg.py:275-284)
            log.debug("round", "attack", trial=trial, round=rnd,
                      sender=sender, recv=recv, action=effect_names(a))
        elif kind == 5:  # round receive (tfg.py:294)
            log.debug("round", "receive", trial=trial, round=rnd,
                      sender=sender, recv=recv, v=v, accepted=bool(a),
                      reason=_REASONS[b])
        elif kind == 6:  # rebroadcast (tfg.py:229)
            log.debug("round", "send", trial=trial, round=rnd,
                      sender=sender, v=v, p_size=a, l_size=b,
                      broadcast=True)
        elif kind == 9:  # deferred receive (racy_mode="defer", D1)
            log.debug("round", "receive", trial=trial, round=rnd,
                      sender=sender, recv=recv, v=v, accepted=bool(a),
                      reason=_REASONS[b], deferred=True)
        elif kind == 10:  # packet queued for the next round (D1)
            log.debug("round", "late defer", trial=trial, round=rnd,
                      sender=sender, recv=recv)
    flush_pending()


def run_trial_native(
    cfg: QBAConfig,
    key: jax.Array,
    log=None,
    trial: int = 0,
) -> dict:
    """One protocol execution in the C++ runtime; returns the rank-0
    summary dict (same shape as
    :func:`qba_tpu.backends.local_backend.run_trial_local`).

    Delegates to :func:`run_trials_native` with a singleton batch so the
    per-trial key-tree derivation exists exactly once.  With ``log``,
    the C engine records its protocol event trail (the reference's
    mpi_print sites, ``tfg.py:190,203,229,275-284,294``) into a trace
    buffer decoded here into the same event grammar the local backend
    emits; the host-side phases (dishonesty, particles, commander state,
    verdict) are emitted from the presampled arrays."""
    trace = None
    if log is not None:
        # Capacity: step2+3a (2/lieutenant) + per round: <= n_pk deliveries
        # per receiver, each <= 4 records (attack + late-defer in the
        # original round, the deferred kind-9 re-delivery in the next,
        # or attack + receive + rebroadcast), + vi snapshot headers and
        # up to w value records per rank.
        n_lieu = cfg.n_lieutenants
        per_round = n_lieu * (n_lieu * cfg.slots * 4 + 1 + cfg.w)
        trace = np.zeros(
            ((2 * n_lieu + cfg.n_rounds * per_round), _TRACE_REC),
            dtype=np.int32,
        )
    res = run_trials_native(cfg, key[None], n_threads=1, trace=trace)
    w, n_lieu = cfg.w, cfg.n_lieutenants
    out = {
        "success": bool(res["success"][0]),
        "decisions": [int(x) for x in res["decisions"][0]],
        "honest": [bool(h) for h in res["honest"][0]],
        "v_comm": int(res["v_comm"][0]),
        "vi": [
            {int(x) for x in range(w) if res["vi"][0, i, x]}
            for i in range(n_lieu)
        ],
        "overflow": bool(res["overflow"][0]),
    }
    if log is not None:
        from qba_tpu.backends.local_backend import (
            emit_host_phases,
            emit_verdict,
        )

        # Host-side phases from the presampled arrays, via the shared
        # emitters (rank-indexed honesty like the other backends).
        honest_r = np.concatenate(
            [[True], res["honest"][0].astype(bool)]
        )
        v_sent_l = [int(x) for x in res["v_sent"][0]]
        emit_host_phases(cfg, log, trial, honest_r, res["lists"][0],
                         out["v_comm"], v_sent_l)
        if res["trace_len"][0] >= trace.shape[0]:
            log.warning("round", "trace truncated", trial=trial)
        _emit_trace(cfg, log, trial, trace[: res["trace_len"][0]])
        emit_verdict(log, trial, out["decisions"], out["honest"],
                     out["success"])
    return out


@functools.partial(jax.jit, static_argnums=0)
def _batch_presample(cfg: QBAConfig, keys: jax.Array):
    """All trials' pre-sampled randomness in one jitted batch (the same
    per-trial key tree, vmapped)."""
    def one(key):
        k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
        honest = assign_dishonest(cfg, k_dis)
        lists = generate_lists_for(cfg, k_lists)[0]
        v_sent, v_comm = commander_orders(cfg, k_comm, honest[1])
        ctx = adversary_ctx(cfg, k_rounds, v_sent)
        return (
            honest, lists, v_sent, v_comm,
            _attack_triples(cfg, k_rounds, ctx),
        )

    return jax.vmap(one)(keys)


def run_trials_native(
    cfg: QBAConfig,
    keys: jax.Array | None = None,
    n_threads: int = 0,
    trace: np.ndarray | None = None,
) -> dict:
    """Monte-Carlo batch on the C++ runtime's threaded executor.

    Randomness is pre-sampled in one jitted batch (identical key tree to
    the other backends), then ``qba_run_trials`` fans the trials out over
    a host thread pool (``n_threads <= 0`` = hardware concurrency).
    Returns a dict of stacked arrays: ``success [n]``, ``decisions
    [n, n_parties]``, ``honest [n, n_parties]``, ``v_comm [n]``, ``vi
    [n, n_lieutenants, w]``, ``overflow [n]``, ``success_rate``.

    ``trace`` (int32 ``[cap, 7]``, single-trial batches only) routes the
    run through ``qba_run_trial`` with the C engine's protocol event
    trail recorded into it; only then does the result also include
    ``trace_len`` plus the presampled ``lists``/``v_sent`` the trail
    renderer needs (a plain Monte-Carlo batch would otherwise retain
    large host arrays nobody reads).
    """
    from qba_tpu.backends.jax_backend import trial_keys

    lib = load()
    if keys is None:
        keys = trial_keys(cfg)
    n = keys.shape[0]
    honest, lists, v_sent, v_comm, attacks = (
        np.asarray(x) for x in _batch_presample(cfg, keys)
    )

    n_lieu, w = cfg.n_lieutenants, cfg.w
    honest_a, honest_p = _u8(honest)
    lists_a, lists_p = _i32(lists)
    vs_a, vs_p = _i32(v_sent)
    vc_a, vc_p = _i32(v_comm)
    at_a, at_p = _i32(attacks)
    decisions = np.zeros((n, cfg.n_parties), dtype=np.int32)
    vi = np.zeros((n, n_lieu, w), dtype=np.uint8)
    flags = np.zeros((n, 2), dtype=np.int32)

    trace_len = None
    if trace is not None:
        if n != 1:
            raise ValueError("trace capture needs a single-trial batch")
        if trace.dtype != np.int32 or trace.ndim != 2 or trace.shape[1] != 7:
            raise ValueError("trace must be int32 [cap, 7]")
        trace_len = np.zeros((1,), dtype=np.int32)
        rc = lib.qba_run_trial(
            cfg.n_parties,
            cfg.size_l,
            cfg.n_dishonest,
            w,
            cfg.slots,
            int(cfg.racy_mode == "defer"),
            honest_p,
            lists_p,
            vs_p,
            int(vc_a[0]),
            at_p,
            decisions.ctypes.data_as(_i32p),
            vi.ctypes.data_as(_u8p),
            flags.ctypes.data_as(_i32p),
            trace.ctypes.data_as(_i32p),
            trace.shape[0],
            trace_len.ctypes.data_as(_i32p),
        )
    else:
        rc = lib.qba_run_trials(
            n,
            n_threads,
            cfg.n_parties,
            cfg.size_l,
            cfg.n_dishonest,
            w,
            cfg.slots,
            int(cfg.racy_mode == "defer"),
            honest_p,
            lists_p,
            vs_p,
            vc_p,
            at_p,
            decisions.ctypes.data_as(_i32p),
            vi.ctypes.data_as(_u8p),
            flags.ctypes.data_as(_i32p),
        )
    if rc != 0:
        raise RuntimeError(f"qba_run_trials failed with rc={rc}")

    out = {
        "success": flags[:, 0].astype(bool),
        "decisions": decisions,
        "honest": honest_a[:, 1:].astype(bool),
        "v_comm": vc_a,
        "vi": vi.astype(bool),
        "overflow": flags[:, 1].astype(bool),
        "success_rate": float(flags[:, 0].mean()),
    }
    if trace is not None:
        # Only the single-trial trace path reads these; a large
        # Monte-Carlo batch would otherwise retain
        # n_trials x (n_parties+1) x size_l of host memory nobody uses.
        out["lists"] = lists_a
        out["v_sent"] = vs_a
        out["trace_len"] = trace_len
    return out
