"""Batched Monte-Carlo runner — backend=jax.

The reference runs one trial per ``mpiexec`` invocation; here a trial is a
pure function of its key, so a Monte-Carlo sweep is ``vmap`` + ``jit``
(SURVEY §2 "Parallelism strategies": the trial axis replaces mpiexec
ranks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from qba_tpu.config import QBAConfig
from qba_tpu.rounds import PartitionHints, TrialResult, run_trial


@struct.dataclass
class MonteCarloResult:
    """Aggregate over a trial batch."""

    trials: TrialResult  # all per-trial fields, leading axis = trials
    success_rate: jnp.ndarray  # float32 scalar

    @property
    def n_trials(self) -> int:
        return self.trials.decisions.shape[0]


def trial_keys(cfg: QBAConfig) -> jax.Array:
    """The batch's key tree root: one key per trial from the config seed."""
    return jax.random.split(jax.random.key(cfg.seed), cfg.trials)


# QBAConfig and PartitionHints are frozen/hashable, so they can be jit
# static arguments — the compiled batch program is cached per (config,
# hints).  This is the single jit entry point for both the local and the
# mesh-sharded (dp/sp) Monte-Carlo runners.
@functools.partial(jax.jit, static_argnums=(0, 2))
def batched_trials(
    cfg: QBAConfig, keys: jax.Array, hints: PartitionHints | None = None
) -> TrialResult:
    return jax.vmap(lambda k: run_trial(cfg, k, hints))(keys)


def aggregate(trials: TrialResult) -> MonteCarloResult:
    """Fold a trial batch into the Monte-Carlo summary (shared by every
    runner: local vmap, dp/sp-sharded, party-sharded spmd)."""
    return MonteCarloResult(
        trials=trials,
        success_rate=jnp.mean(trials.success.astype(jnp.float32)),
    )


# One dispatch for batch + aggregate: on remote-tunnel backends every
# dispatched computation pays a fixed round-trip (~60-100 ms observed), so
# running ``aggregate``'s reduction as a second op outside the jit cost
# ~15% of the headline batch wall time.
@functools.partial(jax.jit, static_argnums=(0,))
def _run_trials_jit(cfg: QBAConfig, keys: jax.Array) -> MonteCarloResult:
    return aggregate(batched_trials(cfg, keys))


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_trials_packed_jit(
    cfg: QBAConfig, keys: jax.Array, pack: int
) -> MonteCarloResult:
    from qba_tpu.rounds.engine import run_trials_fused_packed

    return aggregate(run_trials_fused_packed(cfg, keys, pack))


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_trials_mega_packed_jit(
    cfg: QBAConfig, keys: jax.Array, pack: int
) -> MonteCarloResult:
    from qba_tpu.rounds.engine import run_trials_mega_packed

    return aggregate(run_trials_mega_packed(cfg, keys, pack))


def run_trials(cfg: QBAConfig, keys: jax.Array | None = None) -> MonteCarloResult:
    """Run ``cfg.trials`` independent protocol executions, batched.

    On the fused or megakernel round engine with a resolved trial-pack
    factor ``k > 1`` that divides the batch, dispatch goes through the
    matching packed runner
    (:func:`qba_tpu.rounds.engine.run_trials_fused_packed` /
    :func:`~qba_tpu.rounds.engine.run_trials_mega_packed` — ``k``
    trials per kernel grid or launch); results are bit-identical to
    the plain vmap path trial for trial."""
    if keys is None:
        keys = trial_keys(cfg)
    from qba_tpu.rounds.engine import resolve_round_engine

    engine = resolve_round_engine(cfg)
    if engine == "pallas_fused":
        from qba_tpu.ops.round_kernel_tiled import resolve_trial_pack

        pack = resolve_trial_pack(cfg)
        if pack > 1 and keys.shape[0] % pack == 0:
            return _run_trials_packed_jit(cfg, keys, pack)
    elif engine == "pallas_mega":
        from qba_tpu.ops.round_kernel_tiled import resolve_trial_pack

        pack = resolve_trial_pack(cfg)
        if pack > 1 and keys.shape[0] % pack == 0:
            return _run_trials_mega_packed_jit(cfg, keys, pack)
    return _run_trials_jit(cfg, keys)


def fence(res):
    """Synchronization fence for wall-clock timing.

    ``jax.block_until_ready`` is NOT a fence on remote-tunnel backends
    (axon): it returns after async dispatch, before the computation runs,
    so timings "measure" only the enqueue (observed: identical sub-ms
    times for any batch size).  Fetching one result to the host is the
    only reliable barrier.  Returns ``res`` unchanged.

    The LAST leaf is fetched so chunked dispatch fences correctly: for a
    list of per-chunk results the last leaf belongs to the last-enqueued
    computation, and the device executes enqueued programs in order, so
    its readback implies every earlier chunk finished (fetching the
    first leaf would stop the clock after chunk 0 with the rest still in
    flight).  Within one computation any leaf is equivalent — outputs
    materialize together at program completion.
    """
    jax.device_get(jax.tree.leaves(res)[-1])
    return res
