"""Message-level pure-Python reference backend — backend=local.

An independent re-implementation of the protocol with the reference's data
model — Python sets of int positions, sets of tuples, per-party mailboxes,
explicit per-packet receive loops (``tfg.py:87-98,185-300,337-348``) —
instead of the vectorized masked arrays of :mod:`qba_tpu.rounds`.

It consumes the *identical* keyed randomness as the jax engine (same key
tree: dishonesty, lists, orders, per-(round, receiver, cell) attack
draws), so for any config and trial key the decisions and verdict must
match the jax engine exactly.  ``tests/test_differential.py`` enforces
this; the backend doubles as the CPU wall-clock baseline for ``bench.py``
(the closest stand-in for the unavailable ``mpiexec`` reference run).
"""

from __future__ import annotations

import itertools
from typing import TYPE_CHECKING

import jax
import numpy as np

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
    adversary_ctx,
    assign_dishonest,
    commander_orders,
    effect_names,
    sample_attacks_round,
)
from qba_tpu.config import QBAConfig
from qba_tpu.qsim import generate_lists_for

if TYPE_CHECKING:  # pragma: no cover - typing only
    from qba_tpu.obs import EventLog



def _consistent(v: int, L: set, w: int) -> bool:
    """The reference predicate over sets of tuples (``tfg.py:87-98``)."""
    if not L:
        return True
    lens = {len(t) for t in L}
    if len(lens) != 1:
        return False
    if not all(0 <= x <= w and x != v for t in L for x in t):
        return False
    n = next(iter(lens))
    return all(
        all(a[k] != b[k] for k in range(n))
        for a, b in itertools.combinations(L, 2)
    )


def presample_trial(cfg: QBAConfig, key: jax.Array):
    """The message-level backends' shared per-trial randomness: the
    identical key tree every engine consumes (dishonesty, lists,
    commander orders, and the rounds key for the per-cell attack
    draws).  Returns ``(honest, lists, v_sent, v_comm, k_rounds, ctx)``
    as host values (numpy / Python ints) plus the strategy context
    (:func:`qba_tpu.adversary.adversary_ctx`; None for strategies that
    need none)."""
    k_dis, k_lists, k_comm, k_rounds = jax.random.split(key, 4)
    honest = np.asarray(assign_dishonest(cfg, k_dis))
    lists = np.asarray(generate_lists_for(cfg, k_lists)[0])
    v_sent_arr, v_comm = commander_orders(
        cfg, k_comm, jax.numpy.asarray(bool(honest[1]))
    )
    ctx = adversary_ctx(cfg, k_rounds, v_sent_arr)
    v_sent = [int(x) for x in np.asarray(v_sent_arr)]
    return honest, lists, v_sent, int(v_comm), k_rounds, ctx


def emit_host_phases(cfg: QBAConfig, log, trial, honest, lists, v_comm,
                     v_sent) -> None:
    """The host-side (rank-0-visible) trail phases shared by the
    message-level backends: per-party dishonesty (``tfg.py:124``),
    particle lists (``tfg.py:159-162``), commander state + equivocation
    (``tfg.py:328-330,169-181``)."""
    for rank in range(1, cfg.n_parties + 1):
        log.debug("dishonesty", "party role", trial=trial, rank=rank,
                  honest=bool(honest[rank]))
    for rank in range(cfg.n_parties + 1):
        row = [int(x) for x in lists[rank][:16]]
        log.debug("particles", "list received", trial=trial, rank=rank,
                  head=row, size_l=cfg.size_l)
    n_qcorr = int(np.sum(lists[0] != lists[1]))
    log.info("step2", "commander order", trial=trial, v=v_comm,
             n_qcorr=n_qcorr, commander_honest=bool(honest[1]))
    if len(set(v_sent)) > 1:
        log.info("step2", "commander equivocates", trial=trial,
                 orders=sorted(set(v_sent)))


def emit_verdict(log, trial, decisions, honest_parties, success) -> None:
    """The rank-0 verdict triple (``tfg.py:360-363``), shared trail
    tail of the message-level backends."""
    log.info(
        "decision", "verdict", trial=trial, decisions=decisions,
        dishonest=[i + 1 for i, h in enumerate(honest_parties) if not h],
        success=success,
    )


def run_trial_local(
    cfg: QBAConfig,
    key: jax.Array,
    log: "EventLog | None" = None,
    trial: int = 0,
) -> dict:
    """One protocol execution over Python sets; returns the rank-0 summary
    (``tfg.py:351-363``) plus diagnostics mirroring TrialResult.

    When ``log`` is given, the full protocol event trail is emitted —
    the structured equivalent of every ``mpi_print`` site in the
    reference: per-party dishonesty (``tfg.py:124``), particle lists
    (``tfg.py:159-162``), commander state + equivocation
    (``tfg.py:328-330,169-181``), packet sends (``tfg.py:203,229``),
    attack actions (``tfg.py:275-284``), per-packet accept/reject with
    the failing condition (``tfg.py:190,294``), per-round accepted-sets,
    and the final decision summary (``tfg.py:360-363``).  Phase
    summaries are INFO; per-packet events are DEBUG.
    """
    honest, lists, v_sent, v_comm, k_rounds, ctx = presample_trial(cfg, key)

    n_lieu, w, slots = cfg.n_lieutenants, cfg.w, cfg.slots
    li = [[int(x) for x in lists[i + 2]] for i in range(n_lieu)]
    vi: list[set] = [set() for _ in range(n_lieu)]
    overflow = False

    if log:
        emit_host_phases(cfg, log, trial, honest, lists, v_comm, v_sent)

    # Step 1b: the commander's recovered Q-correlated positions
    # (tfg.py:325-328).
    isq = {k for k in range(cfg.size_l) if lists[0][k] != lists[1][k]}

    # Step 2 + 3a (tfg.py:166-196): per-sender packet lists; the list index
    # is the mailbox slot (same numbering as the dense mailbox tensor).
    mailbox: list[list] = [[] for _ in range(n_lieu)]
    for i in range(n_lieu):
        p = {k for k in isq if int(lists[1][k]) == v_sent[i]}
        v = v_sent[i]
        if log:
            # tfg.py:203 — the commander's send to lieutenant rank i+2.
            log.debug(
                "step2",
                "send",
                trial=trial,
                sender=1,
                dest=i + 2,
                v=v,
                p_size=len(p),
                l_size=0,
            )
        ell = {tuple(li[i][j] for j in sorted(p))}
        ok = _consistent(v, ell, w)
        if ok:
            vi[i].add(v)
            mailbox[i].append((p, v, ell))
        if log:
            # tfg.py:190 — step 3a receive + accept/reject.
            log.debug(
                "step3a",
                "receive",
                trial=trial,
                rank=i + 2,
                v=v,
                accepted=ok,
                reason="accepted" if ok else "inconsistent",
            )

    # Step 3b (tfg.py:337-348): synchronous rounds.  Attack randomness is
    # the same batched per-round arrays the jax engine draws, indexed per
    # cell — the bit-exact three-way contract.
    #
    # Under racy_mode="defer", a late packet is not lost: it is delivered
    # at the start of the NEXT round's drain — the reference's actual
    # race mechanism, where a packet missing its round's Iprobe drain
    # arrives a round later and the ``len(L) == round+1`` check
    # (tfg.py:294) necessarily rejects it (a once-deferred packet's
    # evidence count is one short of the new round's requirement).
    # Corruption is applied at deferral time with the ORIGINAL round's
    # draws — the reference corrupts at send time, before the race.
    deferred: list[list] = [[] for _ in range(n_lieu)]
    for rnd in range(1, cfg.n_rounds + 1):
        k_round = jax.random.fold_in(k_rounds, rnd)
        a_att, a_rv, a_late = (
            np.asarray(x)
            for x in sample_attacks_round(cfg, k_round, rnd, ctx)
        )
        out: list[list] = [[] for _ in range(n_lieu)]
        next_deferred: list[list] = [[] for _ in range(n_lieu)]

        def lieu_receive(recv, sender_rank, p2, v2, ell2, was_deferred=False):
            """tfg.py:289-300 for one delivered packet."""
            ell2 = set(ell2)
            ell2.add(tuple(li[recv][j] for j in sorted(p2)))
            if not _consistent(v2, ell2, w):
                reason = "inconsistent"
            elif v2 in vi[recv]:
                reason = "duplicate-v"
            elif len(ell2) != rnd + 1:
                reason = "wrong-evidence-len"
            else:
                reason = "accepted"
            if log:
                fields = dict(
                    trial=trial, round=rnd, sender=sender_rank,
                    recv=recv + 2, v=v2,
                    accepted=reason == "accepted", reason=reason,
                )
                if was_deferred:
                    fields["deferred"] = True
                log.debug("round", "receive", **fields)
            if reason == "accepted":
                vi[recv].add(v2)
                if rnd <= cfg.n_dishonest:
                    if len(out[recv]) < slots:
                        out[recv].append((p2, v2, ell2))
                        if log:
                            # tfg.py:229 — the accepted packet is
                            # rebroadcast to every peer.
                            log.debug(
                                "round", "send", trial=trial,
                                round=rnd, sender=recv + 2, v=v2,
                                p_size=len(p2), l_size=len(ell2),
                                broadcast=True,
                            )
                    else:
                        nonlocal overflow
                        overflow = True

        # Deferred arrivals from the previous round drain first (they
        # were in the queue before this round's traffic; deterministic
        # (sender, slot) order per D5).
        for recv in range(n_lieu):
            for sender_rank, p2, v2, ell2 in deferred[recv]:
                lieu_receive(recv, sender_rank, p2, v2, ell2, was_deferred=True)

        for recv in range(n_lieu):
            for sender in range(n_lieu):
                for slot in range(min(slots, len(mailbox[sender]))):
                    if sender == recv:
                        continue
                    p, v, ell = mailbox[sender][slot]
                    cell = sender * slots + slot
                    bits, rand_v = (
                        int(a_att[cell, recv]),
                        int(a_rv[cell, recv]),
                    )
                    late = bool(a_late[cell, recv])  # D1 race modeling
                    if late and cfg.racy_mode == "loss":
                        if log:
                            log.debug(
                                "round", "late loss", trial=trial,
                                round=rnd, sender=sender + 2, recv=recv + 2,
                            )
                        continue
                    p2, v2, ell2 = set(p), v, set(ell)
                    if not honest[sender + 2]:  # tfg.py:271-284
                        if log:
                            # tfg.py:275-284 "The action for general N".
                            log.debug(
                                "round", "attack", trial=trial, round=rnd,
                                sender=sender + 2, recv=recv + 2,
                                action=effect_names(bits),
                            )
                        if bits & DROP_BIT:
                            continue
                        if bits & FORGE_BIT:
                            v2 = rand_v
                        if bits & CLEAR_P_BIT:
                            p2 = set()
                        if bits & CLEAR_L_BIT:
                            ell2 = set()
                        if bits & FORGE_P_BIT:
                            # Worst-case P-set forgery: the fabricated
                            # all-positions mask wins over clear.
                            p2 = set(range(cfg.size_l))
                    if late:  # racy_mode == "defer"
                        if log:
                            log.debug(
                                "round", "late defer", trial=trial,
                                round=rnd, sender=sender + 2, recv=recv + 2,
                            )
                        next_deferred[recv].append(
                            (sender + 2, p2, v2, ell2)
                        )
                        continue
                    lieu_receive(recv, sender + 2, p2, v2, ell2)
        if log:
            for i in range(n_lieu):
                log.debug(
                    "round", "vi", trial=trial, round=rnd, rank=i + 2,
                    vi=sorted(vi[i]),
                )
        mailbox = out
        deferred = next_deferred

    # Decision + verdict (tfg.py:303-306,351-363; empty-Vi sentinel is D2).
    decisions = [v_comm] + [
        min(vi[i]) if vi[i] else cfg.no_decision for i in range(n_lieu)
    ]
    honest_parties = [bool(h) for h in honest[1:]]
    filtered = {d for d, h in zip(decisions, honest_parties) if h}
    if log:
        emit_verdict(log, trial, decisions, honest_parties,
                     len(filtered) == 1)
    return {
        "success": len(filtered) == 1,
        "decisions": decisions,
        "honest": honest_parties,
        "v_comm": v_comm,
        "vi": [set(s) for s in vi],
        "overflow": overflow,
    }
