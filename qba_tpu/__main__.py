"""``python -m qba_tpu`` — see :mod:`qba_tpu.cli`."""

import sys

from qba_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
