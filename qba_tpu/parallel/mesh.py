"""Device-mesh construction for QBA Monte-Carlo sweeps.

The reference's only parallelism is one MPI process per protocol party
(``tfg.py:310-314``; launch line ``README.md:4``).  On TPU the axes invert
into a `jax.sharding.Mesh` whose names map protocol dimensions onto
hardware:

* ``dp`` — Monte-Carlo trials (the axis that replaces ``mpiexec`` ranks);
  embarrassingly parallel, no collectives beyond the final statistics
  reduction.
* ``tp`` — protocol parties (lieutenants): the round-engine analog of
  tensor parallelism; each device owns a contiguous block of lieutenants
  and the per-round mailbox exchange is an ``all_gather`` over this axis
  (see :mod:`qba_tpu.parallel.spmd`) — the collective that replaces the
  reference's point-to-point ``Isend``/``Irecv`` traffic
  (``tfg.py:199-263``).
* ``sp`` — list positions (``sizeL``, the protocol's sequence axis,
  SURVEY §5 "Long-context"): i.i.d. positions shard cleanly; XLA inserts
  the reductions the consistency predicate needs.

Pipeline/expert parallelism have no analog here (no layer or expert
structure exists in the protocol); their absence is deliberate
(SURVEY §2 "Parallelism strategies").
"""

from __future__ import annotations

import math
from collections.abc import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    Args:
      axes: ordered ``{axis_name: size}``.  Sizes must multiply to the
        device count used.  ``None`` means a 1-D ``{"dp": n_devices}``
        mesh.
      devices: devices to lay out (default: all of ``jax.devices()``).

    The axis order is ICI-friendly by convention: put the
    highest-traffic axis (``tp``) last so it maps to the
    fastest-varying / nearest-neighbor device dimension.
    """
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    sizes = list(axes.values())
    total = math.prod(sizes)
    if total != len(devices):
        raise ValueError(
            f"mesh axes {dict(axes)} need {total} devices; got {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def axis_sizes(mesh: Mesh) -> dict[str, int]:
    """``{axis_name: size}`` for a mesh (shared by every runner)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def require_divisible(total: int, divisor: int, what: str, axis: str) -> None:
    """Raise the runners' standard sharding-divisibility error."""
    if total % divisor != 0:
        raise ValueError(f"{what}={total} not divisible by {axis}={divisor}")


def default_mesh_shape(n_devices: int, *, want_tp: bool = False) -> dict[str, int]:
    """A reasonable 2-D factorization of ``n_devices``.

    ``want_tp=False`` → ``{"dp": d, "sp": s}`` (Monte-Carlo + position
    sharding); ``want_tp=True`` → ``{"dp": d, "tp": s}`` (party-sharded
    round engine).  The second axis gets the largest power-of-two factor
    ≤ ``sqrt(n_devices)`` so both axes stay useful.
    """
    second = 1
    while second * 2 <= math.isqrt(n_devices) and n_devices % (second * 2) == 0:
        second *= 2
    name = "tp" if want_tp else "sp"
    return {"dp": n_devices // second, name: second}
