"""Device-mesh construction for QBA Monte-Carlo sweeps.

The reference's only parallelism is one MPI process per protocol party
(``tfg.py:310-314``; launch line ``README.md:4``).  On TPU the axes invert
into a `jax.sharding.Mesh` whose names map protocol dimensions onto
hardware:

* ``dp`` — Monte-Carlo trials (the axis that replaces ``mpiexec`` ranks);
  embarrassingly parallel, no collectives beyond the final statistics
  reduction.
* ``tp`` — protocol parties (lieutenants): the round-engine analog of
  tensor parallelism; each device owns a contiguous block of lieutenants
  and the per-round mailbox exchange is a neighbor-ring shuffle over
  this axis (remote DMA on TPU, ``ppermute`` off-TPU — see
  :mod:`qba_tpu.parallel.ring`; ``tp_comms="all_gather"`` keeps the
  one-shot collective as the escape hatch) — the traffic that replaces
  the reference's point-to-point ``Isend``/``Irecv`` exchange
  (``tfg.py:199-263``).
* ``sp`` — list positions (``sizeL``, the protocol's sequence axis,
  SURVEY §5 "Long-context"): i.i.d. positions shard cleanly; XLA inserts
  the reductions the consistency predicate needs.

Pipeline/expert parallelism have no analog here (no layer or expert
structure exists in the protocol); their absence is deliberate
(SURVEY §2 "Parallelism strategies").
"""

from __future__ import annotations

import math
from collections.abc import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    Args:
      axes: ordered ``{axis_name: size}``.  Sizes must multiply to the
        device count used.  ``None`` means a 1-D ``{"dp": n_devices}``
        mesh.
      devices: devices to lay out (default: all of ``jax.devices()``).

    The axis order is ICI-friendly by convention: put the
    highest-traffic axis (``tp``) last so it maps to the
    fastest-varying / nearest-neighbor device dimension.
    """
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    sizes = list(axes.values())
    total = math.prod(sizes)
    if total != len(devices):
        raise ValueError(
            f"mesh axes {dict(axes)} need {total} devices; got {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def axis_sizes(mesh: Mesh) -> dict[str, int]:
    """``{axis_name: size}`` for a mesh (shared by every runner)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def require_divisible(total: int, divisor: int, what: str, axis: str) -> None:
    """Raise the runners' standard sharding-divisibility error."""
    if total % divisor != 0:
        raise ValueError(f"{what}={total} not divisible by {axis}={divisor}")


def make_hybrid_mesh(
    ici_axes: Mapping[str, int],
    *,
    dcn_axis: str = "dp",
    n_slices: int | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-host / multi-slice mesh: slow DCN hops carry only the
    embarrassingly-parallel axis.

    The reference scales across hosts by launching more MPI ranks over
    whatever interconnect mpiexec finds (``README.md:4``); here the
    slice boundary is explicit.  ``dcn_axis`` (default ``dp`` — trials
    need no per-round communication) spans slices over DCN, while
    ``ici_axes`` (e.g. ``{"dp": 2, "tp": 2}``) lay out within-slice
    devices over ICI, keeping the per-round ``all_gather`` of the
    party-sharded engine on the fast fabric.

    Single-slice processes (tests, the CI dryrun) fall back to
    :func:`make_mesh` with the same axis names, so calling code is
    portable.  On a real multi-slice deployment run
    ``jax.distributed.initialize()`` first.
    """
    if devices is None:
        devices = jax.devices()
    if n_slices is None:
        # Devices carry a per-device slice_index on multi-slice
        # deployments; a single granule (or CPU devices without the
        # attribute) means no DCN boundary exists.
        n_slices = len(
            {getattr(d, "slice_index", 0) or 0 for d in devices}
        )
    if dcn_axis not in ici_axes:
        raise ValueError(
            f"dcn_axis {dcn_axis!r} must be one of the mesh axes "
            f"{tuple(ici_axes)}"
        )
    if n_slices <= 1:
        return make_mesh(dict(ici_axes), devices=devices)
    dev_array = hybrid_device_array(
        ici_axes, dcn_axis=dcn_axis, n_slices=n_slices, devices=devices
    )
    return Mesh(dev_array, tuple(ici_axes.keys()))


def hybrid_device_array(
    ici_axes: Mapping[str, int],
    *,
    dcn_axis: str,
    n_slices: int,
    devices: Sequence,
) -> np.ndarray:
    """The device layout behind :func:`make_hybrid_mesh` (factored out so
    the multi-slice branch is unit-testable with mock devices carrying
    ``slice_index`` — real multi-slice hardware is not available in CI).

    Returns an object ndarray shaped like the final mesh: the ``ici_axes``
    sizes with ``dcn_axis`` multiplied by ``n_slices``.
    """
    shape = dict(ici_axes)
    names = tuple(shape.keys())
    sizes = tuple(shape.values())
    if math.prod(sizes) * n_slices != len(devices):
        raise ValueError(
            f"hybrid mesh {dict(shape)} x {n_slices} slices needs "
            f"{math.prod(sizes) * n_slices} devices; got {len(devices)}"
        )
    if all(hasattr(d, "slice_index") for d in devices):
        from jax.experimental import mesh_utils

        dcn_shape = {a: (n_slices if a == dcn_axis else 1) for a in shape}
        return mesh_utils.create_hybrid_device_mesh(
            sizes,
            dcn_mesh_shape=tuple(dcn_shape.values()),
            devices=devices,
        )

    # Devices without slice metadata (the virtual CPU test mesh): treat
    # contiguous blocks as slices — the dcn factor varies slowest along
    # dcn_axis, so within-slice neighbors stay adjacent on the ICI axes.
    i = names.index(dcn_axis)
    dev_array = np.asarray(devices).reshape((n_slices, *sizes))
    dev_array = np.moveaxis(dev_array, 0, i)
    final = list(sizes)
    final[i] = sizes[i] * n_slices
    return dev_array.reshape(final)


def default_mesh_shape(n_devices: int, *, want_tp: bool = False) -> dict[str, int]:
    """A reasonable 2-D factorization of ``n_devices``.

    ``want_tp=False`` → ``{"dp": d, "sp": s}`` (Monte-Carlo + position
    sharding); ``want_tp=True`` → ``{"dp": d, "tp": s}`` (party-sharded
    round engine).  The second axis gets the largest power-of-two factor
    ≤ ``sqrt(n_devices)`` so both axes stay useful.
    """
    second = 1
    while second * 2 <= math.isqrt(n_devices) and n_devices % (second * 2) == 0:
        second *= 2
    name = "tp" if want_tp else "sp"
    return {"dp": n_devices // second, name: second}
