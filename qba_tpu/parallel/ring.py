"""Neighbor-ring comms for the party-sharded (dp × tp) engine.

The round-9 KI-2 story (docs/PERF.md round 9, docs/KNOWN_ISSUES.md
KI-2): the per-round traffic of :mod:`qba_tpu.parallel.spmd` used to be
one ``jax.lax.all_gather`` over ``tp`` — every device transiently
materializes the FULL mailbox pool, so the per-device footprint carried
a ``(tp - 1) x shard`` comms term that eats the linear-in-tp ceiling
the sharding buys.  The ring shuffle replaces it with ``tp - 1``
neighbor hops through a double-buffered pair of shard-sized slots:
each step every device forwards the shard it last received to its
right neighbor and consumes the one arriving from the left, so at any
instant only ``min(2, tp - 1)`` remote shards are resident next to the
local pool.

Two transports realize the same schedule:

* **TPU** — the Pallas ``pltpu.make_async_remote_copy`` remote-DMA
  kernel (:mod:`qba_tpu.ops.ring_shuffle`), the hot path;
* **off-TPU** (CPU-mesh tests, the multichip dryrun) — the masked
  ``jax.lax.ppermute`` ring in :func:`ring_gather` below, which stages
  the identical hop schedule through XLA collectives.

Both are BIT-IDENTICAL to ``jax.lax.all_gather(x, "tp", tiled=True)``
by construction: hop ``k`` delivers the shard of device
``(my_id - k - 1) mod tp`` and writes it at that device's global
offset, so the assembled buffer is the shards concatenated in tp
order — exactly the tiled gather.  tests/test_parallel.py pins the
equality across engines, party counts, strategies and noise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig

#: The resolved comms vocabulary ("auto" resolves to one of these).
TP_COMMS_CHOICES = ("ring", "all_gather")


def resolve_tp_comms(cfg: QBAConfig) -> str:
    """The comms path the party-sharded engine will use: forced values
    pass through; ``auto`` picks the ring (the KI-2-friendly hot path
    since round 9 — remote DMA on TPU, the ``ppermute`` ring off-TPU).
    ``all_gather`` stays available as the explicit escape hatch and as
    the bit-identity reference."""
    if cfg.tp_comms in TP_COMMS_CHOICES:
        return cfg.tp_comms
    return "ring"


def ring_gather(x: jax.Array, n_tp: int, axis: int = 0,
                axis_name: str = "tp") -> jax.Array:
    """All-gather over ``axis_name`` as ``n_tp - 1`` neighbor ring hops.

    Runs inside ``shard_map``.  Each hop forwards the previously
    received shard to the right neighbor (``ppermute`` with the masked
    cyclic permutation) while depositing the arriving shard at its
    owner's global offset, double-buffer style: the carry holds exactly
    one in-flight shard next to the assembled output.  The result
    equals ``jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)``
    bit-for-bit (the property tests/test_parallel.py pins), it is just
    staged as neighbor traffic — which is what the TPU remote-DMA
    kernel (:mod:`qba_tpu.ops.ring_shuffle`) turns into overlap-able
    ICI hops with O(shard) resident comms buffers.
    """
    if n_tp == 1:
        return x
    my_id = jax.lax.axis_index(axis_name)
    shard = x.shape[axis]
    out_shape = list(x.shape)
    out_shape[axis] = shard * n_tp
    out = jnp.zeros(tuple(out_shape), x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x, my_id * shard, axis)
    perm = [(i, (i + 1) % n_tp) for i in range(n_tp)]
    buf = x
    for step in range(n_tp - 1):
        buf = jax.lax.ppermute(buf, axis_name, perm)
        src = jax.lax.rem(my_id - step - 1 + n_tp, n_tp)
        out = jax.lax.dynamic_update_slice_in_dim(out, buf, src * shard, axis)
    return out
