"""Device-mesh parallelism (SURVEY §7.5).

Replaces the reference's mpiexec process-parallelism (``tfg.py:310-314``)
with a named `jax.sharding.Mesh`: trials over ``dp``, lieutenants over
``tp`` (mailbox exchange = ``all_gather`` riding ICI), list positions
over ``sp``.
"""

from qba_tpu.parallel.mesh import default_mesh_shape, make_hybrid_mesh, make_mesh
from qba_tpu.parallel.montecarlo import run_trials_sharded
from qba_tpu.parallel.spmd import run_trials_spmd

__all__ = [
    "default_mesh_shape",
    "make_hybrid_mesh",
    "make_mesh",
    "run_trials_sharded",
    "run_trials_spmd",
]
