"""Party-sharded round engine under ``shard_map`` (dp × tp).

The reference exchanges votes as point-to-point MPI traffic: each accepted
packet triggers ``nParties-2`` tagged ``Isend`` chains and every
lieutenant drains its queue with ``Iprobe`` (``tfg.py:199-263,337-348``).
Here the lieutenants themselves shard over the mesh's ``tp`` axis: each
device owns a contiguous block of lieutenants (their particle lists,
accepted-sets, and outgoing mailbox rows), and one ``jax.lax.all_gather``
over ``tp`` per voting round materializes the full mailbox on every
device — the entire round's traffic as a single XLA collective riding ICI
instead of O(nParties²) tagged messages.  Trials shard over ``dp`` as
usual.

Numerically identical to the single-device engine for the same keys
(enforced by tests/test_parallel.py): the per-round attack draws are the
same globally-indexed batched arrays every engine consumes
(:func:`qba_tpu.adversary.sample_attacks_round`), so placement cannot
change the randomness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from qba_tpu.adversary import sample_attacks_round
from qba_tpu.backends.jax_backend import MonteCarloResult, aggregate, trial_keys
from qba_tpu.config import QBAConfig
from qba_tpu.parallel.mesh import axis_sizes, require_divisible
from qba_tpu.rounds import Mailbox, TrialResult
from qba_tpu.rounds.engine import (
    finish_trial,
    receiver_round,
    setup_trial,
    step3a_one,
)


def _trial_party_sharded(cfg: QBAConfig, n_tp: int, key: jax.Array) -> TrialResult:
    """One trial with lieutenants sharded over the bound ``tp`` mesh axis.

    Runs inside ``shard_map`` (and under ``vmap`` over local trials).
    Phase structure mirrors :func:`qba_tpu.rounds.engine.run_trial`; the
    setup phases are replicated per device (same key → same values), the
    round loop is genuinely distributed.  Replicating setup is deliberate:
    the factorized sampler is O(n_parties * size_l) integer work —
    negligible next to the round loop — and identical keys keep the spmd
    path bit-identical to the single-device engine (the property
    tests/test_parallel.py pins).
    """
    n_local = cfg.n_lieutenants // n_tp
    honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = setup_trial(cfg, key)

    # This device's block of lieutenants.
    start = jax.lax.axis_index("tp") * n_local
    my_ids = start + jnp.arange(n_local)
    my_p = jax.lax.dynamic_slice_in_dim(p_rows, start, n_local, 0)
    my_v = jax.lax.dynamic_slice_in_dim(v_sent, start, n_local, 0)
    my_li = jax.lax.dynamic_slice_in_dim(lieu_lists, start, n_local, 0)

    # Step 3a (tfg.py:185-196) for the local block.
    vi_l, out_cells = jax.vmap(lambda p, v, li: step3a_one(cfg, p, v, li))(
        my_p, my_v, my_li
    )
    mb_local = Mailbox(*out_cells)

    def gather_tp(x):
        return jax.lax.all_gather(x, "tp", axis=0, tiled=True)

    # Step 3b (tfg.py:337-348): each round's traffic = one all_gather of
    # the local mailbox rows over tp (replaces the reference's Isend
    # storm + Iprobe drain + Barrier).
    def round_body(carry, round_idx):
        vi_l, mb_local = carry
        mb_full = jax.tree.map(gather_tp, mb_local)
        k_round = jax.random.fold_in(k_rounds, round_idx)
        # Same batched round draws as the single-device engines; each
        # device consumes its own receivers' rows, so placement cannot
        # change the randomness.
        draws = sample_attacks_round(cfg, k_round)
        my_draws = tuple(
            jax.lax.dynamic_slice_in_dim(d, start, n_local, 1) for d in draws
        )
        vi_l, out_cells, ovf = jax.vmap(
            lambda d, r, vrow, li: receiver_round(
                cfg, round_idx, d, r, vrow, li, mb_full, honest
            ),
            in_axes=(1, 0, 0, 0),
        )(my_draws, my_ids, vi_l, my_li)
        return (vi_l, Mailbox(*out_cells)), jnp.any(ovf)

    (vi_l, _), overflows = jax.lax.scan(
        round_body, (vi_l, mb_local), jnp.arange(1, cfg.n_rounds + 1)
    )

    # Recombine the accepted-sets so every device holds the full decision
    # vector, then decide + verdict as usual.  Scatter-into-zeros + psum
    # rather than all_gather: psum provably erases the tp-varying axis,
    # so the static replication checker (shard_map's check_vma) can
    # verify the outputs are replicated over tp — all_gather's output is
    # equally replicated but the checker cannot prove it.  The extra
    # traffic is negligible (a [n_lieu, w] int grid per trial).
    full = jnp.zeros((cfg.n_lieutenants, cfg.w), jnp.int32)
    full = jax.lax.dynamic_update_slice_in_dim(
        full, vi_l.astype(jnp.int32), start, axis=0
    )
    vi = jax.lax.psum(full, "tp") != 0
    overflow = jax.lax.psum(jnp.any(overflows).astype(jnp.int32), "tp") > 0
    return finish_trial(cfg, vi, v_comm, honest, overflow)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _spmd_batch(cfg: QBAConfig, mesh: Mesh, keys: jax.Array) -> TrialResult:
    n_tp = axis_sizes(mesh)["tp"]
    key_spec = P("dp") if "dp" in mesh.axis_names else P()

    def body(local_keys):
        return jax.vmap(lambda k: _trial_party_sharded(cfg, n_tp, k))(local_keys)

    # check_vma stays ON: the trial body ends in psums over tp, which the
    # replication checker can statically verify (see _trial_party_sharded).
    shard = jax.shard_map(
        body, mesh=mesh, in_specs=key_spec, out_specs=key_spec
    )
    return shard(keys)


def run_trials_spmd(
    cfg: QBAConfig,
    mesh: Mesh,
    keys: jax.Array | None = None,
) -> MonteCarloResult:
    """Monte-Carlo sweep with trials over ``dp`` and lieutenants over ``tp``.

    Requires ``cfg.trials`` divisible by the ``dp`` size and
    ``cfg.n_lieutenants`` divisible by the ``tp`` size.
    """
    if keys is None:
        keys = trial_keys(cfg)
    axes = axis_sizes(mesh)
    if "tp" not in axes:
        raise ValueError(
            f"run_trials_spmd needs a 'tp' mesh axis; got axes {tuple(axes)}. "
            "For trial-only sharding use run_trials_sharded."
        )
    dp, tp = axes.get("dp", 1), axes["tp"]
    require_divisible(keys.shape[0], dp, "trials", "dp")
    require_divisible(cfg.n_lieutenants, tp, "n_lieutenants", "tp")
    return aggregate(_spmd_batch(cfg, mesh, keys))
