"""Party-sharded round engine under ``shard_map`` (dp × tp).

The reference exchanges votes as point-to-point MPI traffic: each accepted
packet triggers ``nParties-2`` tagged ``Isend`` chains and every
lieutenant drains its queue with ``Iprobe`` (``tfg.py:199-263,337-348``).
Here the lieutenants themselves shard over the mesh's ``tp`` axis: each
device owns a contiguous block of lieutenants (their particle lists,
accepted-sets, and outgoing mailbox rows), and per-round communication
assembles the full mailbox on every device.  Two comms paths realize
that assembly (``cfg.tp_comms``; :mod:`qba_tpu.parallel.ring`):

* ``"ring"`` (the default since round 9) — a double-buffered neighbor
  ring shuffle: ``tp - 1`` hops through 2 shard-sized slots, remote
  DMA on TPU (:mod:`qba_tpu.ops.ring_shuffle`), a masked
  ``jax.lax.ppermute`` ring off-TPU.  Only O(shard) comms bytes are
  resident per hop, which is what makes the KI-2 trial ceiling scale
  ~linearly in tp (docs/KNOWN_ISSUES.md KI-2).
* ``"all_gather"`` — one ``jax.lax.all_gather`` over ``tp`` per voting
  round: a single XLA collective riding ICI instead of O(nParties²)
  tagged messages, but every device transiently materializes all
  ``tp - 1`` remote shards at once.  The escape hatch, and the
  bit-identity reference the ring is pinned against.

Trials shard over ``dp`` as usual, composing the true 2-D dp × tp mesh.

Numerically identical to the single-device engine for the same keys
(enforced by tests/test_parallel.py): the per-round attack draws are the
same globally-indexed batched arrays every engine consumes
(:func:`qba_tpu.adversary.sample_attacks_round`), so placement cannot
change the randomness.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from qba_tpu.adversary import adversary_ctx, sample_attacks_round
from qba_tpu.backends.jax_backend import MonteCarloResult, aggregate, trial_keys
from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBADemotionWarning, warn_and_record
from qba_tpu.parallel.mesh import axis_sizes, require_divisible
from qba_tpu.parallel.ring import resolve_tp_comms, ring_gather
from qba_tpu.rounds import Mailbox, TrialResult
from qba_tpu.rounds.engine import (
    ProtocolCounters,
    _vi_bool,
    finish_trial,
    receiver_round,
    scan_rounds,
    setup_trial,
    step3a_one,
)


def _tiled_check_vma() -> bool:
    """Whether the party-sharded tiled engine runs with shard_map's
    replication checker ON (and the kernels' output vma declared).

    Default: ON on real TPU, OFF in kernel interpret mode (interpret
    stages ref reads as dynamic_slices whose literal indices lack the
    operand's vma, which the checker rejects — CPU-mesh tests and the
    multichip dryrun run interpret).  Round 4 shipped this path
    checker-OFF after a Mosaic ``pvary`` lowering failure; round 5
    found the failure gone once ``out_vma`` is actually threaded into
    the tiled builders (the round-4 code hard-coded ``None``) — see
    docs/KNOWN_ISSUES.md KI-1 and ``examples/tpu_vma_canary.py``, which
    re-validates all three configurations on hardware.
    ``QBA_TILED_CHECK_VMA=0`` force-disables (escape hatch if a future
    toolchain regresses); ``=1`` force-enables (e.g. to probe interpret
    mode after a JAX upgrade)."""
    flag = os.environ.get("QBA_TILED_CHECK_VMA", "")
    if flag == "1":
        return True  # force, even in interpret mode (probe a JAX fix)
    if flag == "0":
        return False
    if flag:  # an escape hatch must fail loudly, not fall back silently
        raise ValueError(
            f"QBA_TILED_CHECK_VMA={flag!r}: expected '0' (force the "
            "replication checker off) or '1' (force it on); unset it "
            "for the default (on for TPU, off for kernel interpret "
            "mode)"
        )
    return jax.default_backend() == "tpu"  # interpret mode: off


def _make_gather_tp(
    n_tp: int,
    comms: str,
    vma_axes: frozenset | None,
    mesh_axes: tuple[str, ...],
):
    """The per-round tp assembly primitive, resolved once per trace:
    ``gather_tp(x, axis)`` == ``all_gather(x, "tp", axis, tiled=True)``
    bit-for-bit on every path — only the traffic pattern differs.

    ``"ring"`` on TPU is the remote-DMA kernel
    (:mod:`qba_tpu.ops.ring_shuffle`; one launch per pool leaf per
    round, counted by the KI-5 launch model); off-TPU it is the
    ``ppermute`` ring — bit-identical, and the only transport an
    emulated CPU mesh can execute (remote DMA has no interpret path).
    """
    if comms == "ring" and jax.default_backend() == "tpu":
        from qba_tpu.ops.ring_shuffle import build_ring_gather

        ring = build_ring_gather(
            n_tp, axis_name="tp", mesh_axes=mesh_axes, out_vma=vma_axes,
        )

        def gather_tp(x, axis=0):
            return ring(x, axis=axis)

    elif comms == "ring":

        def gather_tp(x, axis=0):
            return ring_gather(x, n_tp, axis=axis)

    else:

        def gather_tp(x, axis=0):
            return jax.lax.all_gather(x, "tp", axis=axis, tiled=True)

    return gather_tp


def _trial_party_sharded(
    cfg: QBAConfig,
    n_tp: int,
    key: jax.Array,
    engine: str = "xla",
    vma_axes: frozenset | None = None,
    tiled_out_vma: frozenset | None = None,
    comms: str = "all_gather",
    mesh_axes: tuple[str, ...] = ("dp", "tp"),
) -> TrialResult:
    """One trial with lieutenants sharded over the bound ``tp`` mesh axis.

    Runs inside ``shard_map`` (and under ``vmap`` over local trials).
    Phase structure mirrors :func:`qba_tpu.rounds.engine.run_trial`; the
    setup phases are replicated per device (same key → same values), the
    round loop is genuinely distributed.  Replicating setup is deliberate:
    the factorized sampler is O(n_parties * size_l) integer work —
    negligible next to the round loop — and identical keys keep the spmd
    path bit-identical to the single-device engine (the property
    tests/test_parallel.py pins).
    """
    n_local = cfg.n_lieutenants // n_tp
    honest, lieu_lists, p_rows, v_sent, v_comm, k_rounds = setup_trial(cfg, key)
    # Strategy context (collude target / adaptive v_sent); replicated
    # per device like the rest of setup — same key, same values, so the
    # spmd draws stay bit-identical to the single-device engines.
    ctx = adversary_ctx(cfg, k_rounds, v_sent)

    # This device's block of lieutenants.
    start = jax.lax.axis_index("tp") * n_local
    my_ids = start + jnp.arange(n_local)
    my_p = jax.lax.dynamic_slice_in_dim(p_rows, start, n_local, 0)
    my_v = jax.lax.dynamic_slice_in_dim(v_sent, start, n_local, 0)
    my_li = jax.lax.dynamic_slice_in_dim(lieu_lists, start, n_local, 0)

    # Step 3a (tfg.py:185-196) for the local block.
    vi_l, out_cells = jax.vmap(lambda p, v, li: step3a_one(cfg, p, v, li))(
        my_p, my_v, my_li
    )
    mb_local = Mailbox(*out_cells)

    gather_tp = _make_gather_tp(n_tp, comms, vma_axes, mesh_axes)

    # Step 3b (tfg.py:337-348): each round's traffic = one tp assembly
    # of the local mailbox rows (ring shuffle or all_gather — see
    # _make_gather_tp; both replace the reference's Isend storm +
    # Iprobe drain + Barrier).  Four bit-identical engines,
    # like the single-device path: vectorized XLA, the fused monolithic
    # Pallas round kernel, the packet-tiled kernel pair, or the fused
    # single-launch round kernel — each in a party-sharded variant
    # where the device's kernels drain only its receiver block against
    # the gathered global mailbox/pool.
    if engine == "pallas_mega" and jax.default_backend() != "tpu":
        # The sharded megakernel's in-loop ring is remote DMA, which
        # has no interpret path on an emulated mesh; the fused
        # per-round schedule is its bit-identical transport twin (same
        # verdict/rebuild algebra, same draws, same segment-compacted
        # pool layout), so the CPU equivalence suites exercise the same
        # math the TPU megakernel runs.  A transport substitution, not
        # a capability demotion — no warning (the ``ppermute`` twin of
        # :mod:`qba_tpu.ops.ring_shuffle` is the precedent).
        engine = "pallas_fused"

    if engine == "pallas_mega":
        # One launch per trial on the tp mesh: the entry decode, the
        # ``n_rounds * (tp - 1)`` in-kernel ring hops, and every voting
        # round run inside a single pallas_call per device — the KI-5
        # end state, replacing the recorded spmd demotion that ran the
        # per-round fused kernel here through round 10.
        from qba_tpu.ops.round_kernel_tiled import (
            honest_cells as honest_cells_fn,
            resolve_verdict_variant,
            sharded_mega_plan,
        )
        from qba_tpu.ops.trial_megakernel import (
            build_sharded_trial_megakernel,
        )
        from qba_tpu.rounds.engine import _stacked_draws

        # _resolve_spmd_engine only selects this engine with a plan in
        # hand (estimate-gated; no compile probe exists for remote DMA
        # under shard_map — a dispatch failure degrades loudly through
        # run_trials_spmd's fallback).
        blk_d, blk_v = sharded_mega_plan(cfg, n_tp)
        variant = resolve_verdict_variant(cfg, n_recv=n_local)
        mega = build_sharded_trial_megakernel(
            cfg, blk_d, blk_v, n_tp=n_tp, variant=variant,
            out_vma=tiled_out_vma, axis_name="tp", mesh_axes=mesh_axes,
        )
        honest_cells = honest_cells_fn(honest, cfg)
        # The same pre-stacked fold_in draw slabs the single-device
        # megakernel consumes, sliced to this shard's receiver columns
        # — placement cannot change the randomness.
        att_s, rv_s, late_s = (
            jax.lax.dynamic_slice_in_dim(d, start, n_local, 2)
            .astype(jnp.int32)
            for d in _stacked_draws(cfg, k_rounds, ctx)
        )
        vi_i32, _, mega_ovf = mega(
            my_p, my_li, my_v, honest_cells, att_s, rv_s, late_s
        )
        vi_l = vi_i32 != 0
        overflows = mega_ovf
        cst = None
    elif engine == "pallas":
        from qba_tpu.ops.round_kernel import (
            build_round_step,
            honest_packets,
            pack_mailbox,
        )

        step = build_round_step(
            cfg,
            interpret=jax.default_backend() != "tpu",
            n_recv=n_local,
            out_vma=vma_axes,
        )
        honest_pk = honest_packets(honest, cfg)
        n_c = n_local * cfg.slots

        def pack_local(mb):
            return pack_mailbox(mb, n_c, cfg.max_l, cfg.size_l)

        def round_body(carry, round_idx):
            vi_i32, packed_local = carry
            # The gathered global mailbox in kernel layout: device
            # blocks concatenate in tp order = global packet-major
            # (sender, slot) order.
            packed_full = tuple(
                gather_tp(x, axis=1 if i == 0 else 0)
                for i, x in enumerate(packed_local)
            )
            k_round = jax.random.fold_in(k_rounds, round_idx)
            draws = sample_attacks_round(cfg, k_round, round_idx, ctx)
            att, rv, late = (
                jax.lax.dynamic_slice_in_dim(d, start, n_local, 1)
                for d in draws
            )
            out = step(
                round_idx, start, *packed_full, my_li, vi_i32, honest_pk,
                att.astype(jnp.int32), rv.astype(jnp.int32),
                late.astype(jnp.int32),
            )
            return (out[6], tuple(out[:6])), out[7][0, 0] > 0

        init = (vi_l.astype(jnp.int32), pack_local(mb_local))
        (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
        vi_l = vi_i32 != 0
    elif engine == "pallas_fused":
        # The fused single-launch engine's party-sharded variant: same
        # local-pool / all_gather dance as the tiled branch below, but
        # verdict + rebuild run in ONE pallas_call per round (the
        # device's kernel drains its receiver block against the
        # gathered global pool and writes the rebuilt local pool
        # directly).  Trial packing stays single-device — under
        # shard_map the trial axis is dp-sharded outside this body.
        from qba_tpu.ops.round_kernel_tiled import (
            build_fused_round_kernel,
            honest_cells as honest_cells_fn,
            pool_from_step3a,
            resolve_fused_block,
            resolve_tiled_block,
            resolve_verdict_variant,
        )

        interpret = jax.default_backend() != "tpu"
        variant = resolve_verdict_variant(cfg, n_recv=n_local)
        blk_v = resolve_tiled_block(cfg, n_recv=n_local)
        blk_d = resolve_fused_block(cfg, n_recv=n_local)
        if blk_d is None:
            # Same demotion discipline as the single-device engine
            # (run_rounds_fused): the two-kernel tiled path is the
            # probe-demotion target.
            warn_and_record(
                f"party-sharded fused round kernel unavailable at "
                f"(n_parties={cfg.n_parties}, size_l={cfg.size_l}, "
                f"slots={cfg.slots}, n_local={n_local}); demoting to "
                "the two-kernel tiled path",
                QBADemotionWarning,
                site="parallel.spmd._trial_party_sharded",
                stacklevel=2,
                engine_from="pallas_fused",
                engine_to="pallas_tiled",
                n_parties=cfg.n_parties,
                size_l=cfg.size_l,
                slots=cfg.slots,
                n_local=n_local,
            )
            return _trial_party_sharded(
                cfg, n_tp, key, "pallas_tiled", vma_axes, tiled_out_vma,
                comms, mesh_axes,
            )
        fused = build_fused_round_kernel(
            cfg, blk_d, blk_v, interpret=interpret, n_recv=n_local,
            out_vma=tiled_out_vma, variant=variant,
        )
        pool_l = pool_from_step3a(
            cfg, out_cells, start=start, n_recv=n_local
        )
        honest_cells = honest_cells_fn(honest, cfg)

        def round_body(carry, round_idx):
            vi_i32, pool_l = carry
            pool_g = tuple(
                gather_tp(x, axis=1 if i == 0 else 0)
                for i, x in enumerate(pool_l)
            )
            k_round = jax.random.fold_in(k_rounds, round_idx)
            draws = sample_attacks_round(cfg, k_round, round_idx, ctx)
            att_c, rv_c, late_c = (
                jax.lax.dynamic_slice_in_dim(d, start, n_local, 1)
                .astype(jnp.int32)
                for d in draws
            )
            pool_new, vi_i32, ovf = fused(
                round_idx, start, *pool_g, my_li, my_li, vi_i32,
                honest_cells, att_c, rv_c, late_c,
            )
            return (vi_i32, pool_new), ovf

        init = (vi_l.astype(jnp.int32), pool_l)
        (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
        vi_l = vi_i32 != 0
    elif engine == "pallas_tiled":
        # The packet-tiled engine's party-sharded variant: each device
        # keeps a LOCAL compacted pool (its own receivers' outgoing
        # packets, global cell ids); one all_gather over tp per round
        # concatenates the segments into the full pool in global
        # (sender, slot) order — per-segment live prefixes with dead
        # capacity between them, which the verdict kernel's block-skip
        # test already handles (it reads the block's sent flags, not a
        # global count).  The verdict kernel drains only the local
        # receiver block; the rebuild compacts the accepted packets
        # back into the local pool.  Mirrors tfg.py:337-348 semantics
        # at the reference's multi-process shape (README.md:3-4).
        from qba_tpu.ops.round_kernel_tiled import (
            META_CELL,
            build_rebuild_kernel,
            build_verdict_kernel,
            honest_cells as honest_cells_fn,
            pool_from_step3a,
            rebuild_pool,
            resolve_rebuild_block,
            resolve_tiled_block,
            resolve_verdict_variant,
        )

        interpret = jax.default_backend() != "tpu"
        # out_vma powers shard_map's replication checker (ON by default
        # on TPU since round 5; resolved by the caller so the flag is
        # part of the jit cache key — see _spmd_batch); None when the
        # checker is off, where the declarations would be dead
        # machinery.  KI-1 contract, machine-checked: every builder call
        # in this module must pass a non-None-literal out_vma=, and the
        # builders must thread it into vma_struct/promote_vma — the
        # lint's AST + sentinel audits fail CI on a revert
        # (qba_tpu/analysis/vma.py, docs/ANALYSIS.md).
        out_vma = tiled_out_vma
        # Resolve the accept-path variant explicitly so the kernel built
        # here matches the one the block plan probed (the party-sharded
        # engine stays in the group family; on TPU the probe may demote
        # to "group-serial").
        variant = resolve_verdict_variant(cfg, n_recv=n_local)
        blk = resolve_tiled_block(cfg, n_recv=n_local)
        verdict = build_verdict_kernel(
            cfg, blk, interpret=interpret, n_recv=n_local,
            out_vma=out_vma, variant=variant,
        )
        blk_d = resolve_rebuild_block(cfg, n_recv=n_local)
        rebuild_k = (
            build_rebuild_kernel(
                cfg, blk_d, interpret=interpret, n_recv=n_local,
                out_vma=out_vma,
            )
            if blk_d is not None
            else None
        )
        pool_l = pool_from_step3a(
            cfg, out_cells, start=start, n_recv=n_local
        )
        honest_cells = honest_cells_fn(honest, cfg)

        def round_body(carry, round_idx):
            vi_i32, pool_l = carry
            pool_g = tuple(
                gather_tp(x, axis=1 if i == 0 else 0)
                for i, x in enumerate(pool_l)
            )
            k_round = jax.random.fold_in(k_rounds, round_idx)
            draws = sample_attacks_round(cfg, k_round, round_idx, ctx)
            att_c, rv_c, late_c = (
                jax.lax.dynamic_slice_in_dim(d, start, n_local, 1)
                .astype(jnp.int32)
                for d in draws
            )
            acc, vi_i32 = verdict(
                round_idx, start, *pool_g, my_li,
                vi_i32, honest_cells, att_c, rv_c, late_c,
            )
            if rebuild_k is not None:
                pool_new, ovf = rebuild_k(
                    round_idx, start, *pool_g, my_li, acc,
                    att_c, rv_c, honest_cells,
                )
            else:
                # The XLA rebuild consumes pool-ordered draws.
                cell = pool_g[3][:, META_CELL]
                pool_new, ovf = rebuild_pool(
                    cfg, round_idx, pool_g, my_li, acc,
                    jnp.take(att_c, cell, axis=0),
                    jnp.take(rv_c, cell, axis=0),
                    jnp.take(honest_cells, cell, axis=0),
                    start=start, n_recv=n_local,
                )
            return (vi_i32, pool_new), ovf

        # Step 3a's local rows feed the local pool; vi carries int32.
        init = (vi_l.astype(jnp.int32), pool_l)
        (vi_i32, _), overflows, cst = scan_rounds(cfg, round_body, init)
        vi_l = vi_i32 != 0
    else:

        def round_body(carry, round_idx):
            vi_l, mb_local = carry
            mb_full = jax.tree.map(gather_tp, mb_local)
            k_round = jax.random.fold_in(k_rounds, round_idx)
            # Same batched round draws as the single-device engines; each
            # device consumes its own receivers' rows, so placement cannot
            # change the randomness.
            draws = sample_attacks_round(cfg, k_round, round_idx, ctx)
            my_draws = tuple(
                jax.lax.dynamic_slice_in_dim(d, start, n_local, 1)
                for d in draws
            )
            vi_l, out_cells, ovf = jax.vmap(
                lambda d, r, vrow, li: receiver_round(
                    cfg, round_idx, d, r, vrow, li, mb_full, honest
                ),
                in_axes=(1, 0, 0, 0),
            )(my_draws, my_ids, vi_l, my_li)
            return (vi_l, Mailbox(*out_cells)), jnp.any(ovf)

        (vi_l, _), overflows, cst = scan_rounds(
            cfg, round_body, (vi_l, mb_local)
        )

    # Recombine the accepted-sets so every device holds the full decision
    # vector, then decide + verdict as usual.  Scatter-into-zeros + psum
    # rather than all_gather: psum provably erases the tp-varying axis,
    # so the static replication checker (shard_map's check_vma) can
    # verify the outputs are replicated over tp — all_gather's output is
    # equally replicated but the checker cannot prove it.  The extra
    # traffic is negligible (a [n_lieu, w] int grid per trial).
    full = jnp.zeros((cfg.n_lieutenants, cfg.w), jnp.int32)
    full = jax.lax.dynamic_update_slice_in_dim(
        full, vi_l.astype(jnp.int32), start, axis=0
    )
    vi = jax.lax.psum(full, "tp") != 0
    overflow = jax.lax.psum(jnp.any(overflows).astype(jnp.int32), "tp") > 0
    counters = (
        _merge_counters_tp(cfg, n_tp, start, cst, vi, overflows)
        if cst is not None
        else None
    )
    return finish_trial(cfg, vi, v_comm, honest, overflow, counters)


def _merge_counters_tp(
    cfg: QBAConfig,
    n_tp: int,
    start: jax.Array,
    cst,
    vi: jax.Array,
    overflows: jax.Array,
) -> ProtocolCounters:
    """Merge the shard-local :class:`ProtocolCounters` state into the
    replicated full-grid counters.  psum-only (scatter-into-zeros +
    psum), the same discipline as the vi recombination above: psum
    provably erases the tp-varying axis, so shard_map's replication
    checker (check_vma) can verify the counters are replicated over tp
    — a pmax would be equally correct but unprovable."""
    (first_l, high_l), accepts_l = cst
    # first_accept_round uses -1 as "never accepted"; shift by +1 so the
    # scatter's zero fill is the not-my-receiver value, psum, shift back.
    shifted = jnp.zeros((cfg.n_lieutenants, cfg.w), jnp.int32)
    shifted = jax.lax.dynamic_update_slice_in_dim(
        shifted, first_l + 1, start, axis=0
    )
    first_accept = jax.lax.psum(shifted, "tp") - 1
    # slot_high_water is a scalar per shard: one lane of an [n_tp]
    # vector, psum replicates the vector, max reduces it.
    lanes = jnp.zeros((n_tp,), jnp.int32)
    lanes = jax.lax.dynamic_update_slice(
        lanes, high_l[None], (jax.lax.axis_index("tp"),)
    )
    high_water = jnp.max(jax.lax.psum(lanes, "tp"))
    per_round = jnp.any(
        jnp.reshape(_vi_bool(overflows), (cfg.n_rounds, -1)), axis=1
    )
    return ProtocolCounters(
        first_accept_round=first_accept,
        accept_counts=jnp.sum(vi, axis=-2, dtype=jnp.int32),
        accepts_per_round=jax.lax.psum(accepts_l, "tp"),
        slot_high_water=high_water,
        overflow_rounds=jax.lax.psum(per_round.astype(jnp.int32), "tp") > 0,
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 3, 4, 5))
def _spmd_batch(
    cfg: QBAConfig,
    mesh: Mesh,
    keys: jax.Array,
    engine: str = "xla",
    check_vma: bool = True,
    comms: str = "all_gather",
) -> TrialResult:
    """``check_vma`` must be resolved by the CALLER (see
    :func:`_resolve_check_vma`) so it participates in the jit cache
    key: resolved inside the traced body, toggling the
    ``QBA_TILED_CHECK_VMA`` escape hatch after a first compile would be
    silently ignored by the cache — which would, among other things,
    turn the hardware canary's decisive step into a false pass.
    ``comms`` is resolved by the caller too (same cache-key argument;
    :func:`qba_tpu.parallel.ring.resolve_tp_comms`)."""
    n_tp = axis_sizes(mesh)["tp"]
    key_spec = P("dp") if "dp" in mesh.axis_names else P()

    vma_axes = frozenset(mesh.axis_names)
    tiled_out_vma = vma_axes if check_vma else None
    mesh_axes = tuple(mesh.axis_names)

    def body(local_keys):
        return jax.vmap(
            lambda k: _trial_party_sharded(
                cfg, n_tp, k, engine, vma_axes, tiled_out_vma, comms,
                mesh_axes,
            )
        )(local_keys)

    shard = _shard_map(
        body, mesh=mesh, in_specs=key_spec, out_specs=key_spec,
        check_vma=check_vma,
    )
    return shard(keys)


def _shard_map(body, *, mesh, in_specs, out_specs, check_vma):
    """``jax.shard_map`` across jax versions: older builds expose it
    only at ``jax.experimental.shard_map`` and name the replication
    checker ``check_rep`` instead of ``check_vma``."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma,
            )
        except AttributeError:
            pass  # deprecated stub that raises on access
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def _resolve_check_vma(engine: str) -> bool:
    """shard_map replication checking is ON for every engine on real
    TPU (since round 5 — including the tiled engine, whose round-4
    Mosaic ``pvary`` failure disappeared once out_vma was actually
    threaded into its builders; docs/KNOWN_ISSUES.md KI-1): the trial
    body ends in psums over tp, which the checker statically verifies,
    and each Pallas kernel is an opaque call with declared output vma.
    One JAX limitation forces it OFF in kernel interpret mode (CPU
    tests/dryrun): interpret stages ref reads as dynamic_slices whose
    literal indices lack the operand's vma, which the checker rejects.
    The tiled engine additionally honors the ``QBA_TILED_CHECK_VMA``
    escape hatch (:func:`_tiled_check_vma`)."""
    if engine in ("pallas_tiled", "pallas_fused", "pallas_mega"):
        return _tiled_check_vma()
    return not (engine == "pallas" and jax.default_backend() != "tpu")


def run_trials_spmd(
    cfg: QBAConfig,
    mesh: Mesh,
    keys: jax.Array | None = None,
) -> MonteCarloResult:
    """Monte-Carlo sweep with trials over ``dp`` and lieutenants over ``tp``.

    Requires ``cfg.trials`` divisible by the ``dp`` size and
    ``cfg.n_lieutenants`` divisible by the ``tp`` size.
    """
    if keys is None:
        keys = trial_keys(cfg)
    axes = axis_sizes(mesh)
    if "tp" not in axes:
        raise ValueError(
            f"run_trials_spmd needs a 'tp' mesh axis; got axes {tuple(axes)}. "
            "For trial-only sharding use run_trials_sharded."
        )
    dp, tp = axes.get("dp", 1), axes["tp"]
    require_divisible(keys.shape[0], dp, "trials", "dp")
    require_divisible(cfg.n_lieutenants, tp, "n_lieutenants", "tp")
    engine = _resolve_spmd_engine(cfg, cfg.n_lieutenants // tp)
    comms = resolve_tp_comms(cfg)
    try:
        return aggregate(
            _spmd_batch(
                cfg, mesh, keys, engine, _resolve_check_vma(engine), comms
            )
        )
    except Exception as e:
        # The residual probe-context gap (ADVICE r2 item 1): the kernel
        # probes compile standalone, not under the vma-annotated
        # shard_map context the real call uses, so a probe-pass /
        # shard_map-fail config can still surface here — and the ring
        # kernel adds a comms dimension to the same gap (remote DMA has
        # no compile probe at all).  AUTO-selected knobs degrade loudly
        # to their conservative values — the XLA engine, the all_gather
        # collective; an explicitly forced knob re-raises (an explicit
        # knob never silently means something weaker,
        # docs/DIVERGENCES.md D1).
        fb_engine = engine if cfg.round_engine != "auto" else "xla"
        fb_comms = comms if cfg.tp_comms != "auto" else "all_gather"
        if (fb_engine, fb_comms) == (engine, comms):
            raise
        warn_and_record(
            f"party-sharded ({engine!r}, {comms!r}) dispatch failed "
            f"under shard_map; falling back to ({fb_engine!r}, "
            f"{fb_comms!r}): {e!r:.500}",
            QBADemotionWarning,
            site="parallel.spmd.run_trials_spmd",
            stacklevel=2,
            engine_from=engine,
            engine_to=fb_engine,
            comms_from=comms,
            comms_to=fb_comms,
            error=repr(e)[:500],
        )
        return aggregate(
            _spmd_batch(
                cfg, mesh, keys, fb_engine, _resolve_check_vma(fb_engine),
                fb_comms,
            )
        )


def _resolve_spmd_engine(cfg: QBAConfig, n_local: int) -> str:
    """Engine for the party-sharded round loop: forced engines pass
    through (every Pallas engine family has a party-sharded variant —
    including, since round 11, the trial megakernel with its in-kernel
    neighbor ring); ``auto`` on TPU follows the same flat preference
    order as the single-device
    :func:`~qba_tpu.rounds.engine.resolve_round_engine` (packet-tiled
    first everywhere since round 4, the fused per-round kernel above
    it, the sharded trial megakernel above both where its plan is
    admitted, XLA last), probing the LOCAL-receiver kernel variants.

    A forced ``pallas_mega`` demotes loudly — the same two recorded
    reasons as the single-device :func:`~qba_tpu.rounds.engine
    ._demote_mega` — when counters need the host round scan or the
    sharded plan (:func:`~qba_tpu.ops.round_kernel_tiled
    .sharded_mega_plan`) is refused; and ``mega_gen='gf2'`` records a
    generation demotion to the host sampler (the sharded megakernel
    has no gen-fused prologue — the global gen operands would have to
    replicate into every shard's VMEM next to the assembled pool).
    """
    from qba_tpu.ops.round_kernel_tiled import sharded_mega_plan

    n_tp = cfg.n_lieutenants // n_local
    if cfg.round_engine in ("pallas", "pallas_tiled", "pallas_fused"):
        return cfg.round_engine
    if cfg.round_engine == "pallas_mega":
        if cfg.collect_counters:
            warn_and_record(
                "trial megakernel has no host round scan for the "
                "counters wrapper to instrument; collect_counters "
                "demotes to the fused per-round engine under the tp "
                "mesh (bit-identical counters)",
                QBADemotionWarning,
                site="parallel.spmd._resolve_spmd_engine",
                stacklevel=3,
                engine_from="pallas_mega",
                engine_to="pallas_fused",
                reason="counters_need_host_scan",
            )
            return "pallas_fused"
        if sharded_mega_plan(cfg, n_tp) is None:
            warn_and_record(
                "party-sharded trial megakernel unavailable at "
                f"(n_parties={cfg.n_parties}, size_l={cfg.size_l}, "
                f"slots={cfg.slots}, tp={n_tp}); demoting to the "
                "fused per-round engine under the tp mesh",
                QBADemotionWarning,
                site="parallel.spmd._resolve_spmd_engine",
                stacklevel=3,
                engine_from="pallas_mega",
                engine_to="pallas_fused",
                reason="no_sharded_mega_plan",
                n_parties=cfg.n_parties,
                size_l=cfg.size_l,
                slots=cfg.slots,
                n_tp=n_tp,
            )
            return "pallas_fused"
        if cfg.mega_gen == "gf2":
            warn_and_record(
                "mega_gen='gf2' has no party-sharded gen-fused "
                "prologue; step-1 generation stays on the host under "
                "the tp mesh (the sharded megakernel itself still "
                "runs)",
                QBADemotionWarning,
                site="parallel.spmd._resolve_spmd_engine",
                stacklevel=3,
                engine_from="pallas_mega+gen",
                engine_to="pallas_mega",
                reason="no_sharded_gen_fused",
            )
        return "pallas_mega"
    if cfg.round_engine != "auto" or jax.default_backend() != "tpu":
        return "xla"
    from qba_tpu.ops.round_kernel import kernel_compiles
    from qba_tpu.ops.round_kernel_tiled import (
        fused_kernel_plan,
        tiled_kernel_plan,
    )

    if tiled_kernel_plan(cfg, n_recv=n_local) is not None:
        if fused_kernel_plan(cfg, n_recv=n_local) is not None:
            if not cfg.collect_counters and (
                sharded_mega_plan(cfg, n_tp) is not None
            ):
                return "pallas_mega"
            return "pallas_fused"
        return "pallas_tiled"
    if kernel_compiles(cfg, n_recv=n_local):
        return "pallas"
    return "xla"
