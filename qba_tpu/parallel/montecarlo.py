"""Mesh-sharded Monte-Carlo sweeps (dp × sp).

The production distributed path: trials shard over the mesh's ``dp`` axis
(each device runs whole trials — the TPU inversion of "one mpiexec rank
per party", SURVEY §2 "Parallelism strategies"); optionally the list
position axis shards over ``sp`` via an internal sharding constraint, and
XLA inserts the collectives the positionwise reductions need.  Sharding is
expressed with `NamedSharding` annotations and plain ``jit`` — the
scaling-book recipe: pick a mesh, annotate, let the compiler place
collectives.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from qba_tpu.backends.jax_backend import (
    MonteCarloResult,
    aggregate,
    batched_trials,
    trial_keys,
)
from qba_tpu.config import QBAConfig
from qba_tpu.parallel.mesh import axis_sizes, require_divisible
from qba_tpu.rounds import PartitionHints


def run_trials_sharded(
    cfg: QBAConfig,
    mesh: Mesh,
    keys: jax.Array | None = None,
) -> MonteCarloResult:
    """Run ``cfg.trials`` protocol executions sharded over ``mesh``.

    ``mesh`` axes used (others are ignored): ``dp`` shards the trial
    batch (``cfg.trials`` must be divisible by it); ``sp`` — if present
    and > 1 — shards the ``size_l`` position axis inside each trial
    (``cfg.size_l`` must be divisible by it).

    Results are numerically identical to the single-device
    :func:`qba_tpu.backends.jax_backend.run_trials` for the same keys —
    sharding changes placement, not semantics.
    """
    if keys is None:
        keys = trial_keys(cfg)
    axes = axis_sizes(mesh)
    dp = axes.get("dp", 1)
    sp = axes.get("sp", 1)
    require_divisible(keys.shape[0], dp, "trials", "dp")
    require_divisible(cfg.size_l, sp, "size_l", "sp")

    key_spec = P("dp") if "dp" in axes else P()
    keys = jax.device_put(keys, NamedSharding(mesh, key_spec))
    hints = (
        PartitionHints(lists=NamedSharding(mesh, P(None, "sp"))) if sp > 1 else None
    )
    return aggregate(batched_trials(cfg, keys, hints))
