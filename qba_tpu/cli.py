"""Command-line interface: ``python -m qba_tpu {run,bench,sweep}``.

The reference's CLI is ``mpiexec -n <nParties+1> python tfg.py <sizeL>
<nDishonest>`` (``README.md:3-4``, ``tfg.py:366-367``) — the party count
is implied by the MPI world size and there is no validation.  Here the
config is explicit and validated (:class:`qba_tpu.config.QBAConfig`):

* ``run``   — execute trials and print per-trial verdicts in the
  reference's ``Decisions / Dishonests / Success`` format
  (``tfg.py:360-363``) plus the Monte-Carlo aggregate.
* ``bench`` — time the jitted batch and print the throughput line.
* ``sweep`` — chunked, checkpoint-resumable Monte-Carlo sweep (optional
  convergence plot).
* ``study`` — success-rate curve over a swept parameter (e.g. the
  security-parameter study in ``size_l``), optional plot.
* ``lint``  — static KI-1/KI-2/KI-3 invariant check over every traced
  kernel build path (:mod:`qba_tpu.analysis`, docs/ANALYSIS.md); the
  CI gate.  Exit 1 when findings exist, 0 on a clean tree.
* ``serve`` — persistent evaluation service: answers request streams
  (stdin-JSONL or file-queue) with shape-bucketed, double-buffered
  dispatch and per-request run manifests (:mod:`qba_tpu.serve`,
  docs/SERVING.md).
* ``fleet`` — multi-replica serving: a socket/HTTP front-end plus N
  device-pinned serve workers sharing one crash-hardened file queue,
  with target-aware admission (:mod:`qba_tpu.serve.fleet`,
  docs/SERVING.md "Fleet").
"""

from __future__ import annotations

import argparse
import contextlib
import sys
from typing import Sequence

from qba_tpu.config import QBAConfig
from qba_tpu.native import NativeUnavailableError
from qba_tpu.obs.plots import PlottingUnavailableError
from qba_tpu.serve import timing as _timing
from qba_tpu.stats.estimators import success_rate as _est_success_rate


def _add_config_args(p: argparse.ArgumentParser, trials_default: int) -> None:
    p.add_argument(
        "--n-parties", type=int, required=True,
        help="number of generals incl. the commander (reference: mpiexec -n = n_parties+1)",
    )
    p.add_argument(
        "--size-l", type=int, required=True,
        help="security parameter: particle-list length (reference argv[1])",
    )
    p.add_argument(
        "--n-dishonest", type=int, default=0,
        help="Byzantine party count (reference argv[2])",
    )
    p.add_argument("--trials", type=int, default=trials_default)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--qsim-path",
        choices=("factorized", "dense", "dense_pallas", "stabilizer"),
        default="factorized",
        help="quantum engine path (dense = joint statevector, validation "
        "only, <=20 qubits; dense_pallas = same on the fused Pallas "
        "kernel; stabilizer = Clifford tableau — executes the actual "
        "joint circuits at any party count, incl. the reference's "
        "48-qubit 11-party scale)",
    )
    p.add_argument(
        "--round-engine",
        choices=(
            "auto", "xla", "pallas", "pallas_tiled", "pallas_fused",
            "pallas_mega",
        ),
        default="auto",
        help="voting-round engine: auto = the fastest engine that "
        "compiles for this config (one-launch trial megakernel first "
        "where its VMEM plan fits, fused single-launch round kernel "
        "next, the packet-tiled kernel pair, monolithic kernel, pure "
        "XLA as the final fallback); all engines are bit-identical",
    )
    p.add_argument(
        "--trial-pack", type=int, default=None,
        help="fused engine only: fold this many trials into one kernel "
        "grid (must divide --trials to take effect); default = "
        "probe-chosen on TPU, 1 off-TPU",
    )
    p.add_argument(
        "--delivery", choices=("sync", "racy"), default="sync",
        help="racy = model the reference's barrier race as per-delivery "
        "loss with prob --p-late (docs/DIVERGENCES.md D1)",
    )
    p.add_argument("--p-late", type=float, default=0.0)
    p.add_argument(
        "--racy-mode", choices=("loss", "defer"), default="loss",
        help="defer = deliver late packets one round later where the "
        "evidence-length check rejects them (the reference's actual race "
        "mechanism; message-level local backend, docs/DIVERGENCES.md D1)",
    )
    p.add_argument(
        "--attack-scope", choices=("delivery", "broadcast"),
        default="delivery",
        help="broadcast = reproduce the reference's shared-object "
        "mutation leak across a broadcast's recipients "
        "(tfg.py:271-284, docs/DIVERGENCES.md D3)",
    )
    p.add_argument(
        "--strategy",
        choices=("reference", "collude", "adaptive", "split"),
        default="reference",
        help="Byzantine strategy family (docs/ARCHITECTURE.md adversary "
        "zoo): reference = the paper's independent random 4-action "
        "attack; collude = traitors forge one shared per-trial target; "
        "adaptive = action law conditions on round phase and received "
        "value; split = commander equivocation + worst-case P-set "
        "forgery.  All strategies run bit-identically on every engine",
    )
    p.add_argument(
        "--p-depolarize", type=float, default=0.0,
        help="per-qubit depolarizing probability before measurement "
        "(imperfect quantum resources; qba_tpu/qsim/noise.py)",
    )
    p.add_argument(
        "--p-measure-flip", type=float, default=0.0,
        help="per-qubit classical readout flip probability",
    )
    p.add_argument(
        "--collect-counters", action="store_true",
        help="emit on-device protocol counters (rounds-to-acceptance, "
        "per-value accept counts, slot high-water mark) as an auxiliary "
        "per-trial output; primary outputs are bit-identical either way "
        "(docs/OBSERVABILITY.md)",
    )


def _config(args: argparse.Namespace, trials: int | None = None) -> QBAConfig:
    return QBAConfig(
        n_parties=args.n_parties,
        size_l=args.size_l,
        n_dishonest=args.n_dishonest,
        trials=trials if trials is not None else args.trials,
        seed=args.seed,
        qsim_path=args.qsim_path,
        round_engine=args.round_engine,
        trial_pack=args.trial_pack,
        delivery=args.delivery,
        p_late=args.p_late,
        racy_mode=args.racy_mode,
        attack_scope=args.attack_scope,
        strategy=args.strategy,
        p_depolarize=args.p_depolarize,
        p_measure_flip=args.p_measure_flip,
        collect_counters=args.collect_counters,
    )


@contextlib.contextmanager
def _telemetry(args: argparse.Namespace, cfg: QBAConfig, command: str):
    """``--telemetry DIR`` -> a live TelemetrySession (manifest + trace
    written at exit, even on failure), else None.  Entered AFTER the
    final config is known — bench presets replace the config, and the
    manifest must fingerprint what actually ran."""
    if not getattr(args, "telemetry", None):
        yield None
        return
    from qba_tpu.obs.manifest import telemetry_session

    with telemetry_session(args.telemetry, cfg, command) as session:
        yield session


def _parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="qba_tpu",
        description="TPU-native detectable Quantum Byzantine Agreement framework",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run trials, print verdicts")
    _add_config_args(run, trials_default=1)
    run.add_argument(
        "--backend", choices=("jax", "local", "native", "mp"),
        default="jax",
        help="jax = vectorized TPU path; local = message-level pure-Python "
        "path; native = C++ host runtime (qba_tpu/native); mp = one OS "
        "process per party over Unix-socket mesh + the C++ PvL wire "
        "codec (the reference's mpiexec runtime shape)",
    )
    run.add_argument(
        "-v", "--verbose", action="store_true", help="debug-level event log"
    )
    run.add_argument(
        "--jsonl", metavar="PATH", default=None, help="write event log as JSONL"
    )
    run.add_argument(
        "--profile-dir", default=None, help="write a JAX profiler trace"
    )
    run.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="write run telemetry into DIR: run_manifest.json (engine/"
        "demotion/probe decisions, validated schema), trace.json "
        "(Chrome trace events, loadable in Perfetto), spans.jsonl "
        "(docs/OBSERVABILITY.md)",
    )
    run.add_argument(
        "--max-verdicts", type=int, default=8,
        help="print at most this many per-trial verdict blocks; with "
        "--backend native/jax and -v/--jsonl, each displayed trial is "
        "re-run serially through a message-level engine to collect its "
        "event trail, so large values cost proportional extra compute",
    )

    bench = sub.add_parser("bench", help="time the jitted Monte-Carlo batch")
    _add_config_args(bench, trials_default=256)
    bench.add_argument("--reps", type=int, default=3)
    bench.add_argument(
        "--scenario",
        choices=("rounds", "resource_gen", "adversary_sweep"),
        default="rounds",
        help="rounds = full protocol Monte-Carlo (rounds/s headline); "
        "resource_gen = list generation only through the qsim dispatch "
        "(shots/s over trials x size_l, with sampler attribution — "
        "combine with --qsim-path stabilizer for the batched GF(2) "
        "engine); adversary_sweep = the (strategy x noise) surface at "
        "the given size_l through qba_tpu.sweep.run_surface, one "
        "kernel_plan-attributed JSON row per cell",
    )
    bench.add_argument("--profile-dir", default=None)
    bench.add_argument(
        "--preset", choices=("northstar",), default=None,
        help="northstar = BASELINE.md config 5 as written: nParties=33, "
        "sizeL=64, nDishonest=10, 1000 trials (chunked; lossless slots)",
    )
    bench.add_argument(
        "--chunk-trials", type=int, default=None,
        help="split the batch into chunks of this many trials (HBM-bound "
        "configs; wall time covers all chunks end to end)",
    )
    bench.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="write run_manifest.json + trace.json + spans.jsonl into "
        "DIR; the manifest also lands under the JSON line's 'manifest' "
        "key (docs/OBSERVABILITY.md)",
    )

    sweep = sub.add_parser("sweep", help="chunked checkpoint-resumable sweep")
    _add_config_args(sweep, trials_default=256)
    sweep.add_argument("--n-chunks", type=int, required=True)
    sweep.add_argument(
        "--checkpoint", metavar="PATH", default=None,
        help="JSON checkpoint; completed chunks are skipped on re-run",
    )
    sweep.add_argument(
        "--plot", metavar="PNG", default=None,
        help="write a Monte-Carlo convergence plot (requires matplotlib)",
    )
    sweep.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="write run_manifest.json + trace.json + spans.jsonl into "
        "DIR; per-chunk dispatch/readback spans nest under the sweep "
        "(docs/OBSERVABILITY.md)",
    )
    sweep.add_argument(
        "--target", metavar="SPEC", default=None,
        help="precision target: run chunks until the stopping rule "
        "resolves instead of the fixed --n-chunks budget.  SPEC is "
        "'decide vs <p> [+-d] [@ NN%%]' (SPRT against threshold p, "
        "fractions like 1/3 allowed) or 'ci_width<=<w> [@ NN%%]' "
        "(anytime-valid CI width rule); --n-chunks becomes the budget "
        "ceiling (docs/STATS.md)",
    )
    sweep.add_argument(
        "--dispatch", choices=("host", "device"), default="host",
        help="'host': per-chunk dispatch with the stopping rule consulted "
        "between chunks (PR 10 behaviour).  'device': compile the "
        "stopping predicate into a single on-device while_loop — one "
        "dispatch for the whole targeted run, stopping at the same "
        "chunk boundary as the host loop for identical keys; requires "
        "--target (docs/STATS.md \"Device-resident stopping\")",
    )
    sweep.add_argument(
        "--resume-force", action="store_true",
        help="when the checkpoint's chunk_trials disagree with this "
        "run's, discard it (with a QBACheckpointMismatch warning) and "
        "re-chunk from scratch instead of erroring; a config "
        "fingerprint mismatch is never forceable",
    )

    lint = sub.add_parser(
        "lint",
        help="static KI-1/KI-2/KI-3 invariant check over every kernel "
        "build path (docs/ANALYSIS.md); exit 1 on findings",
    )
    lint.add_argument(
        "--engines", default=None, metavar="E1,E2,...",
        help="restrict to these build paths "
        "(xla,pallas,pallas_tiled,pallas_fused,pallas_mega,spmd,gf2; "
        "default: all)",
    )
    lint.add_argument(
        "--config", action="append", default=None, metavar="P,L,D",
        dest="lint_configs",
        help="lint one n_parties,size_l,n_dishonest triple instead of "
        "the built-in matrix (repeatable)",
    )
    lint.add_argument(
        "--saved-plans", metavar="PLANS_JSON", default=None,
        help="also lint every shape recorded in a serve warm-start "
        "artifact (<cache-dir>/plans.json) so plans restored from disk "
        "pass the same KI gates as freshly probed ones "
        "(docs/SERVING.md)",
    )
    lint.add_argument(
        "--effects", action="store_true",
        help="also run the KI-5 donation/aliasing audit and the KI-6 "
        "host-sync discipline gate (jaxpr scan-carry/pallas alias "
        "chase + AST sweep of the hot modules + serve dispatch-order "
        "proof; docs/ANALYSIS.md)",
    )
    lint.add_argument(
        "--manifests", action="append", default=None, metavar="GLOB",
        help="also run the KI-8 manifest-CI audit over these run-"
        "manifest JSON files (repeatable; globs allowed): every "
        "*_rate/*_ratio value must be a certified estimate object "
        "with lo/hi bounds, never a bare float (docs/STATS.md)",
    )
    lint.add_argument(
        "--protocol", action="store_true",
        help="also run the KI-10 file-queue protocol pass: bounded "
        "model check of the fleet's claim/reclaim/poison/stop protocol "
        "(exhaustive BFS with minimal counterexample schedules), the "
        "serve/ conformance sweep binding every queue mutation to a "
        "model transition, and the admission-ledger purity proof "
        "(docs/ANALYSIS.md)",
    )
    lint.add_argument(
        "--atlas", metavar="STORE_DIR", default=None, dest="atlas_store",
        help="also run the KI-11 campaign-completeness gate over this "
        "atlas store: every enumerated cube cell certified to its "
        "target or explicitly refused, records content-addressed and "
        "valid, frontier CI widths <= interior per slice "
        "(docs/ATLAS.md)",
    )
    lint.add_argument(
        "--obs", action="store_true",
        help="also run the KI-12 observability-plane audit: mint-site "
        "closure (trace ids born only at the registered request "
        "origins), metric-name registration against the one METRICS "
        "table, trace-context propagation through every queue hop, "
        "and the engine's span wall-clock anchoring "
        "(docs/OBSERVABILITY.md)",
    )
    lint.add_argument(
        "--obs-queue-dir", metavar="DIR", default=None,
        help="KI-12 dynamic half: stitch this fleet queue dir's traces "
        "and fail on orphan spans or closed traces below the span-"
        "coverage floor",
    )
    lint.add_argument(
        "--obs-telemetry", metavar="DIR", default=None,
        help="telemetry root for --obs-queue-dir (worker span files)",
    )
    lint.add_argument(
        "--obs-coverage-floor", type=float, default=None,
        help="span-coverage floor for --obs-queue-dir (default 0.8)",
    )
    lint.add_argument(
        "--findings-json", metavar="PATH", default=None,
        help="write the full report (findings, notes, stats) as JSON "
        "to PATH — the CI lint job uploads this as an artifact",
    )
    lint.add_argument(
        "-v", "--verbose", action="store_true",
        help="print notes (plan predictions, HBM ceilings) even when "
        "there are findings",
    )

    serve = sub.add_parser(
        "serve",
        help="persistent evaluation service: answer EvalRequest streams "
        "with bucketed, double-buffered dispatch (docs/SERVING.md)",
    )
    serve.add_argument(
        "--transport", choices=("jsonl", "file-queue"), default="jsonl",
        help="jsonl = one request per stdin line, one result per stdout "
        "line; file-queue = poll <queue-dir>/inbox for request files, "
        "write results to <queue-dir>/outbox (stop via a 'stop' file)",
    )
    serve.add_argument(
        "--queue-dir", metavar="DIR", default=None,
        help="queue directory (required for --transport file-queue)",
    )
    serve.add_argument(
        "--chunk-trials", type=int, default=64,
        help="trials per device chunk; same-bucket requests are packed "
        "into chunks of this size (partial chunks are padded at flush)",
    )
    serve.add_argument(
        "--depth", type=int, default=2,
        help="double-buffer depth: chunks in flight before the host "
        "reads back the trailing one (1 disables the overlap)",
    )
    serve.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="write one run_manifest.json + spans.jsonl + trace.json "
        "per request under DIR/<request_id>/",
    )
    serve.add_argument(
        "--cache-dir", metavar="DIR", default=None,
        help="warm-start artifact directory: <DIR>/xla holds the "
        "persistent XLA compilation cache, <DIR>/plans.json the saved "
        "resolver plans (loaded at boot, saved at every flush)",
    )
    serve.add_argument(
        "--no-warm-start", action="store_true",
        help="do not restore plans.json at boot (still saved at flush)",
    )
    serve.add_argument(
        "--max-requests", type=int, default=None,
        help="exit after consuming this many requests (CI smoke)",
    )
    serve.add_argument(
        "--poll-s", type=float, default=_timing.WORKER_POLL_S,
        help="file-queue inbox poll interval in seconds",
    )
    serve.add_argument(
        "--reclaim-timeout-s", type=float, default=None,
        help="file-queue crash recovery: claims older than this with no "
        "result are pushed back to the inbox (exponential backoff per "
        "retry; docs/SERVING.md); default: no reclaim",
    )
    serve.add_argument(
        "--max-reclaims", type=int, default=_timing.MAX_RECLAIMS,
        help="reclaim attempts per request file before dead-lettering "
        "it to <queue-dir>/dead with an error result",
    )
    serve.add_argument(
        "--deadline-s", type=float, default=None,
        help="per-request wall-clock deadline: an overdue request gets "
        "a structured error result (with manifest) instead of wedging "
        "the stream; requests can override via their deadline_s field",
    )
    serve.add_argument(
        "--cache-stats", action="store_true",
        help="print the resolver-cache/probe counters (size, cap, "
        "evictions) plus the cache-dir artifact status and exit",
    )
    serve.add_argument(
        "--replica-id", metavar="ID", default=None,
        help="fleet replica identity: stamped on every result/manifest "
        "and used to name this worker's exit summary "
        "(summary-<ID>.json) so N replicas sharing one queue dir "
        "never clobber each other (docs/SERVING.md 'Fleet')",
    )

    fleet = sub.add_parser(
        "fleet",
        help="multi-replica serving: socket/HTTP front-end + N device-"
        "pinned serve workers over one shared file queue, with target-"
        "aware admission (docs/SERVING.md 'Fleet')",
    )
    fleet.add_argument(
        "--queue-dir", metavar="DIR", required=True,
        help="shared queue directory (created if missing); the fleet "
        "summary lands here as fleet_summary.json",
    )
    fleet.add_argument(
        "--replicas", type=int, default=2,
        help="worker processes; each runs the file-queue serve loop "
        "pinned to one device (TPU: chip K via TPU_VISIBLE_CHIPS)",
    )
    fleet.add_argument(
        "--host", default="127.0.0.1",
        help="front-end listen address",
    )
    fleet.add_argument(
        "--port", type=int, default=0,
        help="front-end listen port (0 = ephemeral; the bound port is "
        "printed to stderr at boot)",
    )
    fleet.add_argument(
        "--chunk-trials", type=int, default=64,
        help="trials per device chunk (shared by workers and the "
        "admission price quantizer)",
    )
    fleet.add_argument(
        "--depth", type=int, default=2,
        help="per-replica double-buffer depth",
    )
    fleet.add_argument(
        "--cache-dir", metavar="DIR", default=None,
        help="shared warm-start artifact directory; the plans.json "
        "file lock makes concurrent replica boots/saves safe",
    )
    fleet.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="per-request telemetry root shared by all replicas (each "
        "request dir carries its replica_id)",
    )
    fleet.add_argument(
        "--deadline-s", type=float, default=None,
        help="per-request wall-clock deadline inside each worker",
    )
    fleet.add_argument(
        "--reclaim-timeout-s", type=float,
        default=_timing.RECLAIM_TIMEOUT_S,
        help="crash recovery: claims older than this with no result "
        "are pushed back to the inbox for a surviving replica",
    )
    fleet.add_argument(
        "--max-reclaims", type=int, default=_timing.MAX_RECLAIMS,
        help="reclaim attempts per request before dead-lettering",
    )
    fleet.add_argument(
        "--max-requests", type=int, default=None,
        help="front-end exits after fully answering this many "
        "requests (CI smoke); default: run until SIGINT",
    )
    fleet.add_argument(
        "--no-admission", action="store_true",
        help="disable the admission layer (every request goes straight "
        "to the queue; no pricing, no defer/reject)",
    )
    fleet.add_argument(
        "--capacity-trials", type=int, default=None,
        help="admission window: max priced-but-unsettled trials "
        "fleet-wide (default: replicas * window-chunks * chunk-trials)",
    )
    fleet.add_argument(
        "--window-chunks", type=int, default=8,
        help="per-replica chunks of headroom in the default capacity "
        "window",
    )
    fleet.add_argument(
        "--mesh-dp", type=int, default=None,
        help="dp width of the per-replica device mesh; with --mesh-tp, "
        "admission prices against the SHARDED KI-2 ceiling "
        "(default: single-chip pricing, or the mesh recorded in the "
        "cache dir's plans.json)",
    )
    fleet.add_argument(
        "--mesh-tp", type=int, default=None,
        help="tp (party-sharding) width of the per-replica device mesh",
    )
    fleet.add_argument(
        "--tp-comms", default="ring", choices=("ring", "all_gather"),
        help="comms transport the sharded admission ceiling prices "
        "(ring = the round-9 remote-DMA default)",
    )
    fleet.add_argument(
        "--poll-s", type=float, default=_timing.WORKER_POLL_S,
        help="worker inbox poll interval (the front-end outbox poll "
        "runs at timing.FRONTEND_POLL_S)",
    )
    fleet.add_argument(
        "--platform", default=None,
        help="jax platform for the workers (cpu/tpu); default: "
        "JAX_PLATFORMS if set, else TPU hardware is auto-detected so "
        "replicas get chip-pinned even where jax auto-initializes "
        "TPU without any env var",
    )
    fleet.add_argument(
        "--supervise", action="store_true",
        help="run the self-healing supervisor: heartbeat watchdog "
        "(SIGKILL hung workers), immediate claim release + poison "
        "quarantine on worker death, crash-loop breaker, respawn "
        "with backoff (docs/SERVING.md 'Self-healing')",
    )
    fleet.add_argument(
        "--watchdog-s", type=float, default=_timing.WATCHDOG_S,
        help="base heartbeat staleness budget; the compile phase gets "
        "timing.WATCHDOG_PHASE_SCALE x (cold XLA compiles are slow, "
        "not hung)",
    )
    fleet.add_argument(
        "--breaker-k", type=int, default=_timing.BREAKER_K,
        help="crash-loop breaker: deaths of one replica slot inside "
        "--breaker-window-s that bench it for good",
    )
    fleet.add_argument(
        "--breaker-window-s", type=float,
        default=_timing.BREAKER_WINDOW_S,
        help="crash-loop breaker window (seconds)",
    )
    fleet.add_argument(
        "--poison-threshold", type=int, default=_timing.POISON_THRESHOLD,
        help="worker deaths blamed on one request before it is "
        "quarantined (dead-lettered with a crash report)",
    )
    fleet.add_argument(
        "--max-respawns", type=int, default=_timing.MAX_RESPAWNS,
        help="respawns per replica slot before it is benched",
    )
    fleet.add_argument(
        "--respawn-backoff-s", type=float,
        default=_timing.RESPAWN_BACKOFF_S,
        help="base exponential backoff between respawns of one slot",
    )

    atlas = sub.add_parser(
        "atlas",
        help="4-D validity-atlas campaign: enumerate the (parties x "
        "dishonest x strategy x noise) cube, certify every cell to a "
        "precision target through the fleet, and render the phase "
        "diagram (docs/ATLAS.md)",
    )
    atlas.add_argument(
        "--store", metavar="DIR", required=True,
        help="atlas store directory (content-addressed cell records + "
        "campaign ledger + rendered atlas.json); resumable — an "
        "interrupted campaign restarts from the ledger here",
    )
    atlas.add_argument(
        "--parties", type=int, nargs="+", required=True,
        help="party counts, e.g. --parties 4 7 13 257",
    )
    atlas.add_argument(
        "--dishonest", nargs="+", required=True,
        help="traitor counts (integers) and/or fractions of n "
        "('1/3', '0.4'), resolved per party count, e.g. "
        "--dishonest 0 1 1/3",
    )
    atlas.add_argument(
        "--strategies", nargs="+", default=["reference"],
        help="adversary strategies (the zoo: reference collude "
        "adaptive split)",
    )
    atlas.add_argument(
        "--noise", nargs="+", default=["0:0"], metavar="P:Q",
        help="noise points as p_depolarize:p_measure_flip pairs, e.g. "
        "--noise 0:0 0.01:0 0:0.02",
    )
    atlas.add_argument("--size-l", type=int, default=4, help="protocol sizeL")
    atlas.add_argument("--seed", type=int, default=0, help="campaign seed")
    atlas.add_argument(
        "--target", default="decide vs 1/3 @ 95%",
        help="per-cell precision target (stats target grammar)",
    )
    atlas.add_argument(
        "--budget-trials", type=int, default=1024,
        help="wave-0 per-cell trial budget; unresolved cells escalate",
    )
    atlas.add_argument(
        "--escalation", type=float, default=4.0,
        help="budget multiplier per escalation wave (frontier cells "
        "only — interior cells resolve on wave 0)",
    )
    atlas.add_argument(
        "--max-escalations", type=int, default=2,
        help="escalation waves before a cell is refused as truncated",
    )
    atlas.add_argument(
        "--chunk-trials", type=int, default=64,
        help="trials per device chunk (shared with admission pricing)",
    )
    atlas.add_argument(
        "--engine", default="auto", help="round engine for every cell"
    )
    atlas.add_argument(
        "--executor", choices=("local", "fleet"), default="local",
        help="local = in-process server (tests/smoke); fleet = file-"
        "queue replicas under this driver (needs --queue-dir)",
    )
    atlas.add_argument(
        "--queue-dir", metavar="DIR", default=None,
        help="fleet executor: shared queue directory",
    )
    atlas.add_argument(
        "--replicas", type=int, default=2,
        help="fleet executor: worker processes",
    )
    atlas.add_argument(
        "--supervise", action="store_true",
        help="fleet executor: run the self-healing supervisor "
        "(watchdog, claim release, poison quarantine, respawn)",
    )
    atlas.add_argument(
        "--platform", default=None,
        help="fleet executor: jax platform for workers (cpu/tpu)",
    )
    atlas.add_argument(
        "--cache-dir", metavar="DIR", default=None,
        help="shared warm-start artifact directory",
    )
    atlas.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="per-request telemetry root",
    )
    atlas.add_argument(
        "--capacity-trials", type=int, default=None,
        help="admission window override (default: replicas * 8 chunks)",
    )
    atlas.add_argument(
        "--window-chunks", type=int, default=8,
        help="per-replica chunks of admission headroom",
    )
    atlas.add_argument(
        "--chaos-kill", action="store_true",
        help="fleet executor: SIGKILL one worker after the first "
        "result lands (chaos drill — the supervisor + campaign ledger "
        "must finish the cube anyway)",
    )
    atlas.add_argument(
        "--max-results", type=int, default=None,
        help="interrupt the driver after N processed results (exit 3; "
        "re-run with the same spec to resume from the ledger)",
    )
    atlas.add_argument(
        "--plot", metavar="DIR", default=None,
        help="also render per-slice PNGs + the KI-7 fence figure into "
        "DIR (requires matplotlib)",
    )

    trace = sub.add_parser(
        "trace",
        help="stitch one fleet run's lifecycle events + worker span "
        "files into causal per-request traces; print the summary or "
        "export Perfetto-loadable trace JSON (docs/OBSERVABILITY.md)",
    )
    trace.add_argument(
        "trace_id", nargs="?", default=None,
        help="a trace id (or request id) to select; omitted = all "
        "stitched traces",
    )
    trace.add_argument(
        "--queue-dir", metavar="DIR", required=True,
        help="the fleet queue directory (holds trace-events.jsonl)",
    )
    trace.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="per-request telemetry root with the worker span files; "
        "without it traces stitch from lifecycle events alone",
    )
    trace.add_argument(
        "--out", metavar="PATH", default=None,
        help="write Chrome/Perfetto trace-event JSON here instead of "
        "printing the stitched summary",
    )

    study = sub.add_parser(
        "study", help="success-rate curve over a swept parameter"
    )
    _add_config_args(study, trials_default=256)
    study.add_argument(
        "--param", required=True,
        choices=("size_l", "n_dishonest", "n_parties", "p_late"),
        help="config field to sweep (size_l is the security parameter)",
    )
    study.add_argument(
        "--values", required=True,
        help="comma-separated values, e.g. 1,2,4,8,16,32",
    )
    study.add_argument(
        "--plot", metavar="PNG", default=None,
        help="write the success-rate curve (requires matplotlib)",
    )
    return parser


def _cmd_run(args: argparse.Namespace, out) -> int:
    cfg = _config(args)
    with _telemetry(args, cfg, "run") as session:
        return _run_impl(args, cfg, session, out)


def _run_impl(args: argparse.Namespace, cfg: QBAConfig, session, out) -> int:
    import types

    import jax
    import numpy as np

    from qba_tpu.obs import EventLog, Level, PhaseTimers, profile_trace, render_sweep, render_verdict

    log = EventLog(
        # --jsonl collects the DEBUG trail for export even without -v;
        # only -v streams it live.
        min_level=Level.DEBUG if (args.verbose or args.jsonl) else Level.INFO,
        stream=out,
        stream_level=Level.DEBUG if args.verbose else Level.INFO,
    )
    timers = PhaseTimers(spans=session.spans if session else None)
    log.info("config", "experiment", n_parties=cfg.n_parties, size_l=cfg.size_l,
             n_dishonest=cfg.n_dishonest, w=cfg.w, trials=cfg.trials,
             backend=args.backend, qsim_path=cfg.qsim_path)

    with profile_trace(args.profile_dir):
        if args.backend == "native":
            # The C++ runtime's threaded batch executor.
            from qba_tpu.backends.jax_backend import trial_keys
            from qba_tpu.backends.native_backend import (
                run_trial_native,
                run_trials_native,
            )

            with timers.time("trials"):
                res = run_trials_native(cfg)
            if args.verbose or args.jsonl:
                # Re-run the displayed trials through the C engine's trace
                # path: the presampled randomness is identical, so the
                # per-packet trail matches the batch verdicts exactly.
                keys = trial_keys(cfg)
                for i in range(min(cfg.trials, args.max_verdicts)):
                    run_trial_native(cfg, keys[i], log=log, trial=i)
            for i in range(min(cfg.trials, args.max_verdicts)):
                trial = types.SimpleNamespace(
                    decisions=res["decisions"][i],
                    honest=res["honest"][i],
                    success=res["success"][i],
                    overflow=res["overflow"][i],
                )
                print(render_verdict(cfg, trial, index=i), file=out)
            any_overflow = bool(np.any(res["overflow"]))
            success_rate = res["success_rate"]
        elif args.backend in ("local", "mp"):
            from qba_tpu.backends.jax_backend import trial_keys

            keys = trial_keys(cfg)
            successes = 0
            any_overflow = False
            results: list[dict] = []
            with timers.time("trials"):
                if args.backend == "mp":
                    # ONE persistent party mesh for the whole batch —
                    # the per-trial spawn cost (n_parties processes)
                    # amortizes across the run (round 4, VERDICT item 4).
                    from qba_tpu.backends.mp_backend import run_trials_mp

                    results = run_trials_mp(
                        cfg,
                        [keys[i] for i in range(cfg.trials)],
                        log=log,
                        log_limit=args.max_verdicts,
                    )
                else:
                    from qba_tpu.backends.local_backend import (
                        run_trial_local,
                    )

                    for i in range(cfg.trials):
                        # The event log receives the full per-packet
                        # protocol trail (visible with -v, exported with
                        # --jsonl) for the same trials whose verdicts
                        # are printed — the reference's surface is one
                        # trial per run, and unbounded trails would
                        # flood stdout and skew the timed phase on
                        # large batches.
                        trail = log if i < args.max_verdicts else None
                        results.append(
                            run_trial_local(cfg, keys[i], log=trail, trial=i)
                        )
            for i, r in enumerate(results):
                successes += int(r["success"])
                any_overflow |= r["overflow"]
                if i < args.max_verdicts:
                    trial = types.SimpleNamespace(
                        decisions=np.asarray(r["decisions"]),
                        honest=np.asarray(r["honest"]),
                        success=np.asarray(r["success"]),
                        overflow=np.asarray(r["overflow"]),
                    )
                    print(render_verdict(cfg, trial, index=i), file=out)
            # Single source of truth for empty-run semantics (nan on
            # zero trials) — same helper sweep/serve report through.
            success_rate = _est_success_rate(successes, cfg.trials)
        else:
            from qba_tpu.backends.jax_backend import fence, run_trials, trial_keys

            keys = trial_keys(cfg)
            with timers.time("trials") as sp:
                res = fence(run_trials(cfg, keys))
                # fence() IS the host readback barrier — this span's
                # duration is attributable device time (docs/PERF.md).
                sp.fenced = True
            if args.verbose or args.jsonl:
                # Trail replay: the vectorized engine cannot cheaply emit
                # per-packet events, but for a given trial key the
                # message-level local backend reproduces its decisions
                # exactly (the three-way differential contract) — so the
                # displayed trials replay through it for the full trail
                # (including the racy_mode="defer" mechanism, which the
                # vectorized engine realizes by its provably-equivalent
                # loss form; see docs/DIVERGENCES.md D1).  Same serial
                # re-run cost note as the native path (--max-verdicts).
                from qba_tpu.backends.local_backend import run_trial_local

                dec = np.asarray(res.trials.decisions)
                for i in range(min(cfg.trials, args.max_verdicts)):
                    r = run_trial_local(cfg, keys[i], log=log, trial=i)
                    if r["decisions"] != [int(x) for x in dec[i]]:
                        # Unreachable unless the differential contract is
                        # broken — surface it rather than show a trail
                        # that doesn't match the printed verdicts.
                        log.warning(
                            "decision", "trail replay mismatch", trial=i,
                            replay=r["decisions"],
                            vectorized=[int(x) for x in dec[i]],
                        )
            for i in range(min(cfg.trials, args.max_verdicts)):
                one = jax.tree.map(lambda x: np.asarray(x)[i], res.trials)
                print(render_verdict(cfg, one, index=i), file=out)
            any_overflow = bool(np.any(np.asarray(res.trials.overflow)))
            success_rate = float(res.success_rate)

    if any_overflow:
        log.warning("round", "mailbox slot overflow in some trials")
    print(
        render_sweep(cfg, success_rate, cfg.trials, timers.total("trials")),
        file=out,
    )
    if args.jsonl:
        log.write_jsonl(args.jsonl)
    return 0


def _cmd_bench(args: argparse.Namespace, out) -> int:
    import dataclasses

    from qba_tpu.benchmark import NORTHSTAR, NORTHSTAR_CHUNK

    if args.reps < 1:
        raise ValueError("bench: --reps must be >= 1")
    cfg = _config(args)
    chunk_trials = args.chunk_trials
    if args.preset == "northstar":
        # The shared gate literals (qba_tpu.benchmark.NORTHSTAR).
        cfg = dataclasses.replace(cfg, **NORTHSTAR)
        chunk_trials = chunk_trials or NORTHSTAR_CHUNK
    with _telemetry(args, cfg, "bench") as session:
        if args.scenario == "resource_gen":
            return _bench_resource_gen(args, cfg, session, out)
        if args.scenario == "adversary_sweep":
            return _bench_adversary_sweep(args, cfg, out)
        return _bench_impl(args, cfg, chunk_trials, session, out)


def _bench_impl(
    args: argparse.Namespace,
    cfg: QBAConfig,
    chunk_trials: int | None,
    session,
    out,
) -> int:
    import dataclasses
    import json
    import statistics

    import jax.numpy as jnp

    from qba_tpu.benchmark import measure_batch
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs import PhaseTimers, profile_trace, throughput
    from qba_tpu.obs.manifest import collect_manifest, probe_stats_snapshot
    from qba_tpu.rounds.engine import resolve_round_engine

    timers = PhaseTimers(spans=session.spans if session else None)
    stats_before = probe_stats_snapshot()
    with record_decisions() as decisions:
        if args.profile_dir:
            # Compile + steady-state warmup OUTSIDE the trace so the
            # profile holds only the timed reps.  Shifted seed: the warmup
            # rep must not reuse the traced run's rep-0 keys, or the
            # tunnel's result cache serves that rep in ~0 s (the same
            # dedupe the per-rep fresh keys exist to defeat).
            with timers.time("warmup"):
                measure_batch(
                    dataclasses.replace(cfg, seed=cfg.seed + 10_000),
                    1, chunk_trials,
                )
        with profile_trace(args.profile_dir):
            with timers.time("measure", reps=args.reps) as sp:
                rep_seconds, n_run, results = measure_batch(
                    cfg, args.reps, chunk_trials, warmup=not args.profile_dir
                )
                # measure_batch fences every rep (the shared fence
                # recipe), so this span is attributable device+tunnel
                # time, not async-dispatch enqueue.
                sp.fenced = True
    best = min(rep_seconds)
    th = throughput(cfg, n_run, best)
    overflow = float(
        jnp.mean(
            jnp.concatenate(
                [r.trials.overflow.astype(jnp.float32) for r in results]
            )
        )
    )
    success = float(
        jnp.mean(
            jnp.concatenate(
                [r.trials.success.astype(jnp.float32) for r in results]
            )
        )
    )
    manifest = collect_manifest(
        cfg,
        command="bench",
        decisions=decisions,
        probe_stats_before=stats_before,
        spans=timers.spans,
    )
    print(
        json.dumps(
            {
                "metric": "protocol_rounds_per_sec",
                "value": round(th["rounds_per_sec"], 2),
                "unit": "rounds/s",
                "trials_per_sec": round(th["trials_per_sec"], 2),
                "best_s": round(best, 4),
                "median_s": round(statistics.median(rep_seconds), 4),
                "rep_seconds": [round(t, 4) for t in rep_seconds],
                "engine": resolve_round_engine(cfg),
                "overflow_rate": round(overflow, 4),
                "success_rate": round(success, 4),
                "config": {
                    "n_parties": cfg.n_parties,
                    "size_l": cfg.size_l,
                    "n_dishonest": cfg.n_dishonest,
                    "trials": n_run,
                    "chunk_trials": chunk_trials or cfg.trials,
                },
                # The full dispatch-decision record (engine, demotion
                # chain, block plan, probe-stats delta) next to the
                # metric — docs/OBSERVABILITY.md.
                "manifest": manifest,
            },
            default=str,
        ),
        file=out,
    )
    return 0


def _bench_adversary_sweep(args: argparse.Namespace, cfg: QBAConfig, out) -> int:
    """The (strategy × noise) surface at the CLI config's size_l — one
    JSON row per cell, each carrying the cell's own kernel-plan
    attribution (strategy changes the traced round program: forge-P is
    statically gated into the split-strategy kernels only)."""
    import json
    import time

    from qba_tpu.adversary import STRATEGIES
    from qba_tpu.benchmark import engine_description, kernel_plan
    from qba_tpu.sweep import run_surface

    noise_points = [(0.0, 0.0)]
    if args.p_depolarize > 0.0 or args.p_measure_flip > 0.0:
        noise_points.append((args.p_depolarize, args.p_measure_flip))
    t0 = time.time()
    cells = run_surface(
        cfg,
        strategies=STRATEGIES,
        noise_points=noise_points,
        size_ls=[cfg.size_l],
        n_chunks=1,
        chunk_trials=cfg.trials,
    )
    for cell in cells:
        cfg_cell = cell.result.cfg
        print(
            json.dumps(
                {
                    "metric": "adversary_surface_cell",
                    "strategy": cell.strategy,
                    "p_depolarize": cell.p_depolarize,
                    "p_measure_flip": cell.p_measure_flip,
                    "size_l": cell.size_l,
                    "trials": cell.result.n_trials,
                    "success_rate": round(cell.result.success_rate, 4),
                    "overflow": cell.result.any_overflow,
                    "engine": engine_description(cfg_cell),
                    "kernel_plan": kernel_plan(cfg_cell),
                    "manifest": cell.manifest,
                },
                default=str,
            ),
            file=out,
        )
    print(
        json.dumps(
            {
                "metric": "adversary_surface",
                "cells": len(cells),
                "seconds": round(time.time() - t0, 2),
            }
        ),
        file=out,
    )
    return 0


def _bench_resource_gen(
    args: argparse.Namespace, cfg: QBAConfig, session, out,
) -> int:
    import json
    import statistics

    from qba_tpu.benchmark import measure_resource_gen, qsim_description
    from qba_tpu.diagnostics import record_decisions
    from qba_tpu.obs import PhaseTimers
    from qba_tpu.obs.manifest import collect_manifest, probe_stats_snapshot

    timers = PhaseTimers(spans=session.spans if session else None)
    stats_before = probe_stats_snapshot()
    with record_decisions() as decisions:
        with timers.time("measure", reps=args.reps) as sp:
            rep_seconds, shots = measure_resource_gen(cfg, args.reps)
            sp.fenced = True  # measure_resource_gen fences every rep
    best = min(rep_seconds)
    manifest = collect_manifest(
        cfg,
        command="bench",
        decisions=decisions,
        probe_stats_before=stats_before,
        spans=timers.spans,
    )
    print(
        json.dumps(
            {
                "metric": "resource_shots_per_sec",
                "value": round(shots / best, 2),
                "unit": "shots/s",
                "shots_per_rep": shots,
                "best_s": round(best, 4),
                "median_s": round(statistics.median(rep_seconds), 4),
                "rep_seconds": [round(t, 4) for t in rep_seconds],
                "qsim": qsim_description(cfg),
                "config": {
                    "n_parties": cfg.n_parties,
                    "size_l": cfg.size_l,
                    "n_dishonest": cfg.n_dishonest,
                    "trials": cfg.trials,
                    "total_qubits": cfg.total_qubits,
                    "w": cfg.w,
                    "qsim_path": cfg.qsim_path,
                },
                "manifest": manifest,
            },
            default=str,
        ),
        file=out,
    )
    return 0


def _cmd_sweep(args: argparse.Namespace, out) -> int:
    from qba_tpu.obs import EventLog, PhaseTimers, render_sweep
    from qba_tpu.sweep import run_sweep

    cfg = _config(args)
    with _telemetry(args, cfg, "sweep") as session:
        log = EventLog(stream=out)
        timers = PhaseTimers(spans=session.spans if session else None)
        res = run_sweep(
            cfg,
            n_chunks=args.n_chunks,
            chunk_trials=cfg.trials,
            checkpoint=args.checkpoint,
            log=log,
            timers=timers,
            target=args.target,
            resume_force=args.resume_force,
            dispatch=args.dispatch,
        )
        # Wall time for throughput = dispatch + readback (the two phases
        # are disjoint: dispatch returns at async-enqueue, readback
        # blocks).  A device-resident run has neither — its one fenced
        # loop span covers compile+run+readback end to end.
        seconds = (
            timers.total("dispatch")
            + timers.total("readback")
            + timers.total("device_loop")
        ) or None
        print(
            render_sweep(cfg, res.success_rate, res.n_trials, seconds),
            file=out,
        )
        if res.stop is not None:
            line = (
                f"stop: {res.stop.reason} after {res.stop.n_trials} trials"
            )
            if res.stop.threshold is not None:
                line += f" (threshold {res.stop.threshold:g})"
            est = res.stop.estimate
            if est is not None:
                # The rule's own anytime-valid interval — safe to read
                # at the data-dependent stopping time (docs/STATS.md).
                line += (
                    f"; {100 * est.confidence:g}% CI "
                    f"[{est.lo:.4f}, {est.hi:.4f}]"
                )
            print(line, file=out)
        if session is not None:
            # Certified rates in the telemetry manifest (KI-8): the
            # manifest states its own precision.
            session.extra["stats"] = res.stats_summary()
        if res.any_overflow:
            print("(mailbox slot overflow occurred in some chunks)", file=out)
        if args.plot:
            from qba_tpu.obs.plots import plot_convergence

            print(
                f"convergence plot: {plot_convergence(res, args.plot)}",
                file=out,
            )
    return 0


def _cmd_study(args: argparse.Namespace, out) -> int:
    import dataclasses

    from qba_tpu.backends.jax_backend import run_trials

    import numpy as np

    from qba_tpu.obs.stats import study_breakdown

    cfg = _config(args)
    is_float = args.param == "p_late"
    if is_float and cfg.delivery != "racy":
        cfg = dataclasses.replace(cfg, delivery="racy")
    values = [
        float(x) if is_float else int(x) for x in args.values.split(",")
    ]
    rates = []
    for v in values:
        cfg_v = dataclasses.replace(cfg, **{args.param: v})
        res = run_trials(cfg_v)
        rate = float(res.success_rate)
        rates.append(rate)
        print(f"{args.param}={v}: success_rate={rate:.4f} "
              f"({cfg_v.trials} trials)", file=out)
        # Success decomposed over commander honesty (Wilson 95% —
        # validity is the protocol's actual security property, see
        # docs/VALIDITY.md); printed only when the split is non-trivial.
        if cfg_v.n_dishonest:
            b = study_breakdown(
                np.asarray(res.trials.success),
                np.asarray(res.trials.honest)[:, 0],
            )
            va, ag = b["validity"], b["agreement_dishonest_c"]
            if va["n"]:
                print(
                    f"  validity (honest commander):  "
                    f"{va['rate']:.4f} [{va['lo']:.4f}, {va['hi']:.4f}] "
                    f"({va['k']}/{va['n']})",
                    file=out,
                )
            if ag["n"]:
                print(
                    f"  agreement (dishonest cmdr.):  "
                    f"{ag['rate']:.4f} [{ag['lo']:.4f}, {ag['hi']:.4f}] "
                    f"({ag['k']}/{ag['n']})",
                    file=out,
                )
    if args.plot:
        from qba_tpu.obs.plots import plot_param_study

        path = plot_param_study(
            values, rates, cfg.trials, args.param, args.plot,
            log_x=args.param == "size_l" and min(values) > 0,
        )
        print(f"study plot: {path}", file=out)
    return 0


def _cmd_lint(args: argparse.Namespace, out) -> int:
    from qba_tpu.analysis.driver import (
        lint_configs,
        run_lint,
        saved_plan_configs,
    )

    engines = (
        [e.strip() for e in args.engines.split(",") if e.strip()]
        if args.engines else None
    )
    if args.lint_configs:
        configs = []
        for spec in args.lint_configs:
            try:
                p, l, d = (int(x) for x in spec.split(","))
            except ValueError:
                raise ValueError(
                    f"--config wants n_parties,size_l,n_dishonest; got {spec!r}"
                ) from None
            configs.append((f"({p},{l},{d})", QBAConfig(p, l, d)))
    else:
        configs = lint_configs()
    if args.saved_plans:
        # Shapes a server has actually dispatched (warm-start artifact)
        # get the same gates as the built-in matrix, deduplicated
        # against points already covered.
        covered = {
            (c.n_parties, c.size_l, c.n_dishonest) for _, c in configs
        }
        for label, cfg in saved_plan_configs(args.saved_plans):
            if (cfg.n_parties, cfg.size_l, cfg.n_dishonest) not in covered:
                configs.append((label, cfg))
    report = run_lint(
        configs=configs, engines=engines, effects=args.effects,
        protocol=args.protocol,
    )
    if args.manifests:
        from qba_tpu.analysis.manifests import check_manifest_files

        report.extend(check_manifest_files(args.manifests))
    if args.atlas_store:
        from qba_tpu.analysis.atlas import check_atlas_store

        report.extend(check_atlas_store(args.atlas_store))
    if args.obs:
        from qba_tpu.analysis.obs import check_obs

        report.extend(check_obs())
    if args.obs_queue_dir:
        from qba_tpu.analysis.obs import COVERAGE_FLOOR, check_span_coverage

        report.extend(
            check_span_coverage(
                args.obs_queue_dir,
                telemetry_dir=args.obs_telemetry,
                floor=(
                    args.obs_coverage_floor
                    if args.obs_coverage_floor is not None
                    else COVERAGE_FLOOR
                ),
            )
        )
    print(report.render(verbose=args.verbose), file=out)
    if args.findings_json:
        import dataclasses
        import json

        payload = {
            "schema": "qba-tpu/lint-findings/v1",
            "ok": report.ok,
            "effects": bool(args.effects),
            "protocol": bool(args.protocol),
            "obs": bool(args.obs),
            "findings": [dataclasses.asdict(f) for f in report.findings],
            "notes": report.notes,
            "stats": {
                k: (sorted(v) if isinstance(v, (set, frozenset)) else v)
                for k, v in report.stats.items()
            },
        }
        with open(args.findings_json, "w") as fh:
            json.dump(payload, fh, indent=2)
        print(f"findings json: {args.findings_json}", file=out)
    return 0 if report.ok else 1


def _cmd_trace(args: argparse.Namespace, out) -> int:
    import json

    from qba_tpu.obs.tracing import (
        stitch_traces,
        stitched_chrome_trace,
        trace_summary,
    )

    stitched = stitch_traces(args.queue_dir, telemetry_dir=args.telemetry)
    traces = stitched["traces"]
    selected = sorted(traces)
    if args.trace_id is not None:
        selected = [
            tid for tid, t in traces.items()
            if tid == args.trace_id
            or tid.startswith(args.trace_id)
            or t.get("request_id") == args.trace_id
        ]
        if not selected:
            print(
                f"error: no stitched trace matches {args.trace_id!r} "
                f"({len(traces)} trace(s) in {args.queue_dir})",
                file=sys.stderr,
            )
            return 1
    if args.out:
        chrome = stitched_chrome_trace(stitched, trace_ids=selected)
        with open(args.out, "w") as fh:
            json.dump(chrome, fh, indent=1)
        print(
            json.dumps(
                {
                    "trace_json": args.out,
                    "traces": len(selected),
                    "events": len(chrome["traceEvents"]),
                }
            ),
            file=out,
        )
        return 0
    payload = {
        "summary": trace_summary(stitched),
        "traces": [
            {
                "trace_id": tid,
                "request_id": traces[tid].get("request_id"),
                "closed": traces[tid]["closed"],
                "dur_s": round(traces[tid]["dur"], 6),
                "coverage": traces[tid]["coverage"],
                "segments": traces[tid]["segments"],
                "events": [e["event"] for e in traces[tid]["events"]],
            }
            for tid in selected
        ],
    }
    print(json.dumps(payload, indent=1, default=str), file=out)
    return 0


def _cmd_atlas(args: argparse.Namespace, out) -> int:
    import json
    import threading
    import time

    from qba_tpu.atlas import (
        AtlasStore,
        CampaignDriver,
        CampaignSpec,
        FleetExecutor,
        LocalExecutor,
    )
    from qba_tpu.atlas.cube import parse_dishonest
    from qba_tpu.serve.fleet import AdmissionController

    noise: list[tuple[float, float]] = []
    for tok in args.noise:
        p, sep, q = tok.partition(":")
        if not sep:
            raise ValueError(f"--noise wants p_depolarize:p_measure_flip, got {tok!r}")
        noise.append((float(p), float(q or 0)))
    spec = CampaignSpec(
        parties=tuple(args.parties),
        dishonest=parse_dishonest(args.dishonest),
        strategies=tuple(args.strategies),
        noise_points=tuple(noise),
        size_l=args.size_l,
        seed=args.seed,
        chunk_trials=args.chunk_trials,
        budget_trials=args.budget_trials,
        escalation=args.escalation,
        max_escalations=args.max_escalations,
        target=args.target,
        round_engine=args.engine,
    )
    store = AtlasStore(args.store)
    admission = AdmissionController(
        chunk_trials=args.chunk_trials,
        replicas=args.replicas if args.executor == "fleet" else 1,
        capacity_trials=args.capacity_trials,
        window_chunks=args.window_chunks,
    )
    pool = None
    supervisor = None
    sup_thread = None
    sup_stop = threading.Event()
    on_result = None
    t0 = time.monotonic()
    if args.executor == "fleet":
        if not args.queue_dir:
            raise ValueError("--executor fleet requires --queue-dir")
        from qba_tpu.serve.fleet import FleetSupervisor, ReplicaPool

        executor = FleetExecutor(args.queue_dir)
        pool = ReplicaPool(
            args.queue_dir,
            replicas=args.replicas,
            chunk_trials=args.chunk_trials,
            cache_dir=args.cache_dir,
            telemetry_dir=args.telemetry,
            platform=args.platform,
        )
        if args.supervise:
            supervisor = FleetSupervisor(pool, admission=admission)
        if args.chaos_kill:
            killed = []

            def on_result(count: int, payload: dict) -> None:
                # One SIGKILL, after the first result proves the fleet
                # works — the supervisor + ledger must finish the cube.
                if count == 1 and not killed:
                    alive = pool.alive()
                    if alive:
                        victim = alive[-1]
                        pid = pool.kill(victim)
                        killed.append(victim)
                        print(
                            json.dumps(
                                {"chaos": {"killed": victim, "pid": pid}}
                            ),
                            file=sys.stderr,
                            flush=True,
                        )

        pool.start()
        if supervisor is not None:
            sup_thread = threading.Thread(
                target=supervisor.run, args=(sup_stop,), daemon=True
            )
            sup_thread.start()
    else:
        executor = LocalExecutor(
            chunk_trials=args.chunk_trials,
            cache_dir=args.cache_dir,
            telemetry_dir=args.telemetry,
        )
    driver = CampaignDriver(
        store,
        spec,
        executor,
        admission=admission,
        log=lambda s: print(s, file=sys.stderr, flush=True),
        max_results=args.max_results,
        on_result=on_result,
    )
    try:
        summary = driver.run()
    finally:
        # Stop supervising BEFORE stopping the pool (same ordering as
        # `fleet`: a draining worker must not be watchdogged).
        sup_stop.set()
        if sup_thread is not None:
            sup_thread.join(timeout=30)
        if pool is not None:
            pool.stop()
    summary["elapsed_s"] = time.monotonic() - t0
    if supervisor is not None:
        summary["self_healing"] = supervisor.summary()
    if args.plot:
        from qba_tpu.atlas import plot_slices

        written = plot_slices(store, args.plot)
        if written:
            summary["plots"] = written
        else:
            raise PlottingUnavailableError(
                "--plot requires matplotlib, which is not importable"
            )
    print(json.dumps({"atlas": summary}, indent=1, default=str), file=out)
    if summary.get("interrupted"):
        return 3
    return 0 if summary["open"] == 0 else 1


def _cmd_serve(args: argparse.Namespace, out) -> int:
    import json

    if args.cache_stats:
        import os

        from qba_tpu.compile_cache import plans_path, xla_cache_dir
        from qba_tpu.ops.round_kernel_tiled import resolve_cache_info
        from qba_tpu.serve.persist import saved_configs

        info: dict = {"resolver": resolve_cache_info()}
        if args.cache_dir:
            plans = plans_path(args.cache_dir)
            artifact: dict = {
                "xla_cache_dir": xla_cache_dir(args.cache_dir),
                "plans_path": plans,
                "plans_exists": os.path.exists(plans),
            }
            if artifact["plans_exists"]:
                try:
                    artifact["saved_shapes"] = len(saved_configs(plans))
                except ValueError as e:
                    artifact["plans_error"] = str(e)
            info["cache_dir"] = artifact
        print(json.dumps(info, indent=1, default=str), file=out)
        return 0

    from qba_tpu.serve import QBAServer, serve_file_queue, serve_jsonl

    server = QBAServer(
        chunk_trials=args.chunk_trials,
        depth=args.depth,
        telemetry_dir=args.telemetry,
        cache_dir=args.cache_dir,
        warm_start=not args.no_warm_start,
        deadline_s=args.deadline_s,
        replica_id=args.replica_id,
    )
    if args.transport == "file-queue":
        if not args.queue_dir:
            raise ValueError(
                "serve: --queue-dir is required with --transport file-queue"
            )
        stats = serve_file_queue(
            server,
            args.queue_dir,
            poll_s=args.poll_s,
            max_requests=args.max_requests,
            reclaim_timeout_s=args.reclaim_timeout_s,
            max_reclaims=args.max_reclaims,
        )
    else:
        stats = serve_jsonl(
            server, sys.stdin, out, max_requests=args.max_requests
        )
    # Results went to stdout/outbox; the operator summary goes to
    # stderr so jsonl result streams stay machine-parseable.
    print(json.dumps({"serve_summary": stats}, default=str), file=sys.stderr)
    return 0


def _cmd_fleet(args: argparse.Namespace, out) -> int:
    import json
    import threading
    import time

    from qba_tpu.serve.fleet import (
        AdmissionController,
        FleetFrontend,
        FleetSupervisor,
        ReplicaPool,
        fleet_summary,
        write_fleet_summary,
    )

    # Mesh for sharded admission pricing: explicit flags win; otherwise
    # the mesh recorded in the warm-start artifact (the plans were
    # captured under it, so the priced ceiling matches what dispatch
    # will actually see).
    mesh_shape = None
    tp_comms = args.tp_comms
    if args.mesh_dp is not None or args.mesh_tp is not None:
        mesh_shape = (args.mesh_dp or 1, args.mesh_tp or 1)
    elif args.cache_dir:
        from qba_tpu.serve.persist import saved_mesh

        recorded = saved_mesh(args.cache_dir)
        if recorded is not None:
            mesh_shape = (
                int(recorded.get("dp", 1)), int(recorded.get("tp", 1))
            )
            tp_comms = recorded.get("tp_comms", tp_comms)

    admission = None
    if not args.no_admission:
        admission = AdmissionController(
            chunk_trials=args.chunk_trials,
            replicas=args.replicas,
            capacity_trials=args.capacity_trials,
            window_chunks=args.window_chunks,
            mesh_shape=mesh_shape,
            tp_comms=tp_comms,
        )
    pool = ReplicaPool(
        args.queue_dir,
        replicas=args.replicas,
        chunk_trials=args.chunk_trials,
        depth=args.depth,
        cache_dir=args.cache_dir,
        telemetry_dir=args.telemetry,
        deadline_s=args.deadline_s,
        reclaim_timeout_s=args.reclaim_timeout_s,
        max_reclaims=args.max_reclaims,
        poll_s=args.poll_s,
        platform=args.platform,
        max_respawns=args.max_respawns,
        respawn_backoff_s=args.respawn_backoff_s,
    )
    supervisor = None
    if args.supervise:
        supervisor = FleetSupervisor(
            pool,
            admission=admission,
            watchdog_s=args.watchdog_s,
            breaker_k=args.breaker_k,
            breaker_window_s=args.breaker_window_s,
            poison_threshold=args.poison_threshold,
        )
    frontend = FleetFrontend(
        args.queue_dir,
        admission,
        host=args.host,
        port=args.port,
        max_requests=args.max_requests,
        health_provider=supervisor.health if supervisor else None,
    )
    t0 = time.monotonic()
    pool.start()
    sup_stop = threading.Event()
    sup_thread = None
    if supervisor is not None:
        sup_thread = threading.Thread(
            target=supervisor.run, args=(sup_stop,), daemon=True
        )
        sup_thread.start()
    try:
        port = frontend.start_in_thread()
        print(
            json.dumps(
                {
                    "fleet": {
                        "listening": f"{args.host}:{port}",
                        "replicas": pool.alive(),
                        "queue_dir": args.queue_dir,
                        "supervised": supervisor is not None,
                    }
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        try:
            frontend._thread.join()
        except KeyboardInterrupt:
            frontend.stop_in_thread()
    finally:
        # Stop supervising BEFORE dropping the stop sentinel: workers
        # draining a slow flush must not be watchdogged or "respawned"
        # into a stopping queue.
        sup_stop.set()
        if sup_thread is not None:
            sup_thread.join(timeout=30)
        codes = pool.stop()
    status = frontend.status()
    summary = fleet_summary(
        args.queue_dir,
        admission_summary=admission.summary() if admission else None,
        frontend_status=status,
        elapsed_s=time.monotonic() - t0,
        telemetry_dir=args.telemetry,
        self_healing=supervisor.summary() if supervisor else None,
    )
    summary["replica_exit_codes"] = codes
    if args.cache_dir and mesh_shape is not None:
        # Record the pricing mesh in the warm-start artifact so the
        # next boot admits against the same sharded ceiling without
        # re-passing the flags.
        from qba_tpu.serve.persist import save_plans

        save_plans(
            args.cache_dir,
            mesh={
                "dp": mesh_shape[0],
                "tp": mesh_shape[1],
                "tp_comms": tp_comms,
            },
        )
    path = write_fleet_summary(args.queue_dir, summary)
    print(json.dumps({"fleet_summary": path}), file=sys.stderr)
    return 0


def main(argv: Sequence[str] | None = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    args = _parser().parse_args(argv)
    from qba_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()
    try:
        if args.command == "run":
            return _cmd_run(args, out)
        if args.command == "bench":
            return _cmd_bench(args, out)
        if args.command == "sweep":
            return _cmd_sweep(args, out)
        if args.command == "study":
            return _cmd_study(args, out)
        if args.command == "lint":
            return _cmd_lint(args, out)
        if args.command == "serve":
            return _cmd_serve(args, out)
        if args.command == "fleet":
            return _cmd_fleet(args, out)
        if args.command == "atlas":
            return _cmd_atlas(args, out)
        if args.command == "trace":
            return _cmd_trace(args, out)
    except ValueError as e:  # config validation -> clean CLI failure
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (PlottingUnavailableError, NativeUnavailableError) as e:
        # Optional-dependency conditions (--plot without matplotlib,
        # --backend native without a working toolchain) -> clean usage
        # error.  Deliberately narrow: other RuntimeErrors (XLA execution
        # or native runtime errors) keep their tracebacks.
        print(f"error: {e}", file=sys.stderr)
        return 2
    raise AssertionError(f"unhandled command {args.command}")
