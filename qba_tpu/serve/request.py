"""Request/result model of the evaluation service.

An :class:`EvalRequest` is the ROADMAP item-3 question — "n parties, d
traitors, adversary A: failure probability at sizeL=L?" — as a typed,
transport-friendly record.  It deliberately exposes only the fields a
*caller* owns (protocol shape, adversary model, trials/seed, engine
preference); everything the engine derives (w, slots, kernel plan)
comes back in the per-request run manifest instead.

Identity contract: :meth:`EvalRequest.config` builds the exact
:class:`~qba_tpu.config.QBAConfig` a direct :func:`~qba_tpu.backends.
jax_backend.run_trials` call would use, and the server draws the
request's trial keys from that config's seed with the same key-tree
recipe — so a served result is bit-identical to the direct run
(tests/test_serve.py pins decisions/success across xla and
pallas_fused).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from qba_tpu.config import QBAConfig
from qba_tpu.stats.estimators import success_rate as _success_rate


@dataclasses.dataclass(frozen=True)
class EvalRequest:
    """One evaluation question.  ``request_id`` is caller-chosen and
    opaque; the server echoes it on the result and names the request's
    telemetry directory with it."""

    request_id: str
    n_parties: int
    size_l: int
    n_dishonest: int = 0
    trials: int = 1
    seed: int = 0
    round_engine: str = "auto"
    qsim_path: str = "factorized"
    delivery: str = "sync"
    p_late: float = 0.0
    racy_mode: str = "loss"
    attack_scope: str = "delivery"
    # Adversary zoo + imperfect resources (the ROADMAP item-3 adversary
    # axis): the strategy is part of the bucket identity, so distinct
    # strategies never share a compiled program they shouldn't.
    strategy: str = "reference"
    p_depolarize: float = 0.0
    p_measure_flip: float = 0.0
    tiled_block: int | None = None
    trial_pack: int | None = None
    # Per-request wall-clock deadline (seconds from submit); None defers
    # to the server's default.  An overdue request gets a structured
    # error EvalResult (with manifest) instead of wedging the stream.
    deadline_s: float | None = None
    # Precision target (qba_tpu.stats.parse_target grammar, e.g.
    # "decide vs 1/3 @ 95%" or "ci_width<=0.02"): "run until resolved
    # or deadline".  ``trials`` becomes the budget ceiling; the server
    # stops filling the request once its stopping rule fires and
    # returns the partial prefix with the stop decision (docs/STATS.md).
    target: str | None = None
    # Per-trial decisions are O(trials * n_parties) ints on the wire;
    # callers that only want the rate leave this off.
    return_decisions: bool = False
    # Trace context (docs/OBSERVABILITY.md, schema
    # qba-tpu/trace-context/v1): minted once at the request's origin
    # (fleet frontend intake, or the atlas campaign driver) and adopted
    # — never re-minted — by every hop downstream.  It rides the
    # queue-file JSON so the worker's root span, the supervisor's
    # lifecycle events, and the settle all stitch into one causal
    # trace.  ``parent_span_id`` is the origin's intake span.
    trace_id: str | None = None
    parent_span_id: str | None = None

    def config(self) -> QBAConfig:
        """The request as a validated config — raises ``ValueError``
        exactly where the CLI would (the transport turns that into an
        error result, not a server crash)."""
        return QBAConfig(
            n_parties=self.n_parties,
            size_l=self.size_l,
            n_dishonest=self.n_dishonest,
            trials=self.trials,
            seed=self.seed,
            round_engine=self.round_engine,
            qsim_path=self.qsim_path,
            delivery=self.delivery,
            p_late=self.p_late,
            racy_mode=self.racy_mode,
            attack_scope=self.attack_scope,
            strategy=self.strategy,
            p_depolarize=self.p_depolarize,
            p_measure_flip=self.p_measure_flip,
            tiled_block=self.tiled_block,
            trial_pack=self.trial_pack,
        )

    def fingerprint(self) -> dict[str, Any]:
        """The manifest-grade config fingerprint (explicit fields plus
        derived shape parameters) — reuses the run-manifest's recipe so
        a request and its manifest agree field for field."""
        from qba_tpu.obs.manifest import config_fingerprint

        return config_fingerprint(self.config())

    def to_json(self) -> dict[str, Any]:
        return {"kind": "eval_request", **dataclasses.asdict(self)}

    @classmethod
    def from_json(cls, payload: dict[str, Any]) -> "EvalRequest":
        """Strict decode: unknown keys are an error (a typo'd field
        silently ignored would answer a different question than asked)."""
        data = dict(payload)
        data.pop("kind", None)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown request field(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        if "request_id" not in data:
            raise ValueError("request is missing 'request_id'")
        return cls(**data)


@dataclasses.dataclass
class EvalResult:
    """The answer to one :class:`EvalRequest`.

    ``latency_s`` is the request's span duration (submit -> results on
    host), i.e. the span tree IS the latency instrument — the server's
    p50/p99 summary aggregates exactly these spans
    (docs/SERVING.md).  ``manifest`` is the full validated run manifest
    for this request (schema ``qba-tpu/run-manifest/v1``)."""

    request_id: str
    n_trials: int
    successes: int
    success_rate: float
    any_overflow: bool
    latency_s: float
    engine: str  # resolved engine attribution, e.g. "pallas_fused/group"
    bucket: str  # the shape bucket this request dispatched on
    chunks: int  # device chunks this request's trials spanned
    success: list[bool] = dataclasses.field(default_factory=list)
    decisions: list[list[int]] | None = None
    manifest: dict[str, Any] | None = None
    error: str | None = None
    # Precision-targeted requests only: the StopDecision (as JSON) and
    # the anytime-valid rate estimate at stop.  ``n_trials`` is then the
    # trials actually executed (<= the requested budget).
    stop: dict[str, Any] | None = None
    ci: dict[str, Any] | None = None
    # Fleet attribution (docs/SERVING.md "Fleet"): which pool replica
    # served the request, and how long it sat in the shared queue
    # before a worker claimed it — ``latency_s`` minus ``queue_wait_s``
    # is the replica-side (dispatch + device + readback) share.
    replica_id: str | None = None
    queue_wait_s: float | None = None
    # Typed admission decision (qba_tpu.serve.fleet.admission), attached
    # by the front-end: action, reason, and the priced trial capacity.
    admission: dict[str, Any] | None = None
    # Poison-request quarantine (qba_tpu.serve.fleet.supervisor): a
    # request dead-lettered for killing workers carries the structured
    # blame evidence — ``{blamed_replicas, phases, exit_codes,
    # reclaim_count}`` — so the caller learns *why* it will never be
    # retried, not just that it failed.
    crash_report: dict[str, Any] | None = None
    # The request's trace id, echoed back so the caller (and the
    # frontend's settle event) can resolve the stitched trace without
    # a side lookup.
    trace_id: str | None = None

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["kind"] = "eval_result"
        return d

    @classmethod
    def from_json(cls, payload: dict[str, Any]) -> "EvalResult":
        data = dict(payload)
        data.pop("kind", None)
        return cls(**data)

    @classmethod
    def failure(cls, request_id: str, error: str) -> "EvalResult":
        """An error reply that still round-trips the transport (bad
        request, engine failure) — the stream keeps flowing."""
        return cls(
            request_id=request_id,
            n_trials=0,
            successes=0,
            # Uniform empty-result handling (stats satellite): nan on
            # zero trials, from the single source of truth.
            success_rate=_success_rate(0, 0),
            any_overflow=False,
            latency_s=0.0,
            engine="",
            bucket="",
            chunks=0,
            error=error,
        )


def decode_request_line(line: str) -> EvalRequest:
    """One JSONL transport line -> request (raises ``ValueError`` on
    malformed JSON or unknown/missing fields)."""
    try:
        payload = json.loads(line)
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed request JSON: {e}") from None
    if not isinstance(payload, dict):
        raise ValueError(f"request must be a JSON object, got {payload!r:.80}")
    return EvalRequest.from_json(payload)
