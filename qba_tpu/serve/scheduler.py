"""Shape-bucketing scheduler: requests -> trial-packed device chunks.

The memoized resolvers (PR 2) make same-shape dispatch free — the first
config of a shape pays probes, every later one hits `_RESOLVE_CACHE` —
and jit keys on the config object itself.  So the scheduler's job is to
*manufacture* shape reuse: every incoming request is normalized onto a
bucket config (seed zeroed, trials pinned to the server's chunk size)
and its trials are packed, together with other same-bucket requests,
into fixed-size chunks.  One bucket == one compiled program == one
resolver plan, regardless of how many distinct (seed, trials) requests
flow through it.

Determinism contract (tests/test_serve.py): chunk assembly is a pure
function of the enqueue order — trials are assigned oldest-request
first within the oldest-ready bucket, and the tail of a partial chunk
is padded with zero key rows (computed, then discarded at readback).
No clocks, no hashing order, no jax: this module is plain
numpy-on-host so the policy is unit-testable without a device.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import Deque

import numpy as np

from qba_tpu.config import QBAConfig


def bucket_config(cfg: QBAConfig, chunk_trials: int) -> QBAConfig:
    """The bucket (= jit/resolver) key for ``cfg``: same shape and
    engine knobs, seed zeroed and trials pinned to the chunk size.
    Seed and trial count only affect *which keys* the host feeds in, so
    every config in a bucket shares one compiled program bit-exactly."""
    return dataclasses.replace(cfg, seed=0, trials=chunk_trials)


def bucket_label(bucket: QBAConfig) -> str:
    """Human-readable bucket id used in spans/results, e.g.
    ``5p-L8-d1-auto`` (non-reference strategies get a suffix: the
    strategy is already part of the bucket *identity* via the config
    object — split traces a different kernel — so the label shows it)."""
    label = (
        f"{bucket.n_parties}p-L{bucket.size_l}-d{bucket.n_dishonest}"
        f"-{bucket.round_engine}"
    )
    if bucket.strategy != "reference":
        label += f"-{bucket.strategy}"
    return label


@dataclasses.dataclass(frozen=True)
class Segment:
    """One request's contiguous slice of a chunk: trials
    ``[req_start, req_start+length)`` of ``request_id`` sit at chunk
    rows ``[chunk_start, chunk_start+length)``."""

    request_id: str
    req_start: int
    chunk_start: int
    length: int


@dataclasses.dataclass
class Chunk:
    """One device dispatch: ``key_data`` is the full ``[chunk_trials, 2]``
    uint32 key material (tail rows past ``used`` are padding)."""

    index: int
    bucket: QBAConfig
    key_data: np.ndarray
    segments: list[Segment]

    @property
    def used(self) -> int:
        return sum(s.length for s in self.segments)


@dataclasses.dataclass
class _Queued:
    request_id: str
    key_data: np.ndarray  # [trials, 2] uint32 (jax.random.key_data form)
    order: int  # global arrival index — the determinism anchor
    cursor: int = 0  # trials already assigned to chunks

    @property
    def remaining(self) -> int:
        return len(self.key_data) - self.cursor


class BucketScheduler:
    """FIFO-fair bucketing: :meth:`next_chunk` always serves the bucket
    whose head request arrived earliest, and fills the chunk from that
    bucket's queue in arrival order (a request larger than a chunk
    spans several; a small one shares its chunk with successors)."""

    def __init__(self, chunk_trials: int = 64) -> None:
        if chunk_trials < 1:
            raise ValueError(f"chunk_trials must be >= 1, got {chunk_trials}")
        self.chunk_trials = chunk_trials
        self._queues: OrderedDict[QBAConfig, Deque[_Queued]] = OrderedDict()
        self._arrivals = 0
        self._chunks = 0

    def bucket_for(self, cfg: QBAConfig) -> QBAConfig:
        return bucket_config(cfg, self.chunk_trials)

    def enqueue(
        self, request_id: str, cfg: QBAConfig, key_data: np.ndarray
    ) -> QBAConfig:
        """Queue ``cfg.trials`` trials (``key_data`` rows) under the
        request's bucket; returns the bucket config."""
        # Wire decode: the key table arrives as host numpy from the
        # transport and never lives on the device.
        # qba-lint: sync-ok (host-side wire decode)
        key_data = np.asarray(key_data, dtype=np.uint32)
        if key_data.shape != (cfg.trials, 2):
            raise ValueError(
                f"key_data shape {key_data.shape} != ({cfg.trials}, 2)"
            )
        bucket = self.bucket_for(cfg)
        self._queues.setdefault(bucket, deque()).append(
            _Queued(request_id, key_data, self._arrivals)
        )
        self._arrivals += 1
        return bucket

    def pending_trials(self) -> int:
        return sum(q.remaining for dq in self._queues.values() for q in dq)

    def cancel(self, request_id: str) -> int:
        """Drop every still-queued trial of ``request_id`` (deadline
        expiry); returns how many trials were removed.  Trials already
        assembled into chunks are untouched — their readback segments
        are discarded by the server when the request is no longer
        active."""
        removed = 0
        for dq in self._queues.values():
            keep = deque()
            while dq:
                q = dq.popleft()
                if q.request_id == request_id:
                    removed += q.remaining
                else:
                    keep.append(q)
            dq.extend(keep)
        return removed

    def has_full_chunk(self) -> bool:
        return any(
            sum(q.remaining for q in dq) >= self.chunk_trials
            for dq in self._queues.values()
        )

    def next_chunk(self) -> Chunk | None:
        """Assemble the next chunk (padded if the bucket can't fill it),
        or None when nothing is pending."""
        best: QBAConfig | None = None
        best_order: int | None = None
        for bucket, dq in self._queues.items():
            if not dq:
                continue
            if best_order is None or dq[0].order < best_order:
                best, best_order = bucket, dq[0].order
        if best is None:
            return None
        dq = self._queues[best]
        key_data = np.zeros((self.chunk_trials, 2), dtype=np.uint32)
        segments: list[Segment] = []
        filled = 0
        while dq and filled < self.chunk_trials:
            head = dq[0]
            take = min(head.remaining, self.chunk_trials - filled)
            key_data[filled : filled + take] = head.key_data[
                head.cursor : head.cursor + take
            ]
            segments.append(
                Segment(head.request_id, head.cursor, filled, take)
            )
            head.cursor += take
            filled += take
            if head.remaining == 0:
                dq.popleft()
        chunk = Chunk(self._chunks, best, key_data, segments)
        self._chunks += 1
        return chunk
