"""Network-free transports for the evaluation service.

Tier-1 runs in hermetic CI containers, so the server speaks two
filesystem/pipe protocols instead of sockets:

* **jsonl** — one :class:`EvalRequest` JSON object per stdin line, one
  :class:`EvalResult` JSON object per stdout line (completion order),
  run summary on stderr at EOF.  Composes with shell pipes:
  ``cat requests.jsonl | qba-tpu serve --transport jsonl > results.jsonl``.
* **file-queue** — a queue directory with ``inbox/`` (drop
  ``*.json`` request files; the server claims them atomically by
  rename into ``claimed/``), ``outbox/`` (one result file per request,
  written via temp-file + rename so readers never see partial JSON),
  and a ``stop`` sentinel file that triggers drain + ``summary.json``
  + clean exit.  This is the transport the CI smoke step and
  examples/load_gen.py drive.

Both transports keep the stream flowing on bad input: a malformed or
invalid request becomes an error :class:`EvalResult`, never a server
crash.  Batching policy: requests are pumped as they arrive (full
chunks dispatch immediately); a partial chunk is flushed when the
input goes quiet (EOF on jsonl, an empty poll on file-queue), so tail
requests never wait on traffic that isn't coming.
"""

from __future__ import annotations

import json
import os
import time
from typing import IO, Any, Iterable

from qba_tpu.serve.engine import QBAServer
from qba_tpu.serve.queuefs import (
    FlightRecorder,
    HeartbeatWriter,
    queue_paths,
    request_slug,
    result_path as _result_path_for,
    write_json_atomic,
)
from qba_tpu.serve.request import EvalResult, decode_request_line
from qba_tpu.serve.timing import MAX_RECLAIMS, WORKER_POLL_S

#: Test-only crash hook (the chaos harness's poison-request injector):
#: when this env var is set, a worker that claims a request whose id
#: contains the var's value hard-exits mid-claim — emulating a compile
#: OOM / libtpu abort without needing one.  Unset (production) the
#: check never runs.  examples/load_gen.py --chaos-poison and the CI
#: chaos job set it; the supervisor's quarantine bounds the blast
#: radius to poison_threshold workers (docs/KNOWN_ISSUES.md KI-9).
CRASH_HOOK_ENV = "QBA_TEST_CRASH_HOOK"
CRASH_HOOK_EXIT = 113


def _emit_jsonl(out: IO[str], results: Iterable[EvalResult]) -> int:
    n = 0
    for res in results:
        out.write(json.dumps(res.to_json()) + "\n")
        n += 1
    if n:
        out.flush()
    return n


def serve_jsonl(
    server: QBAServer,
    in_stream: IO[str],
    out_stream: IO[str],
    *,
    max_requests: int | None = None,
) -> dict[str, Any]:
    """Drive ``server`` from a JSONL stream until EOF (or
    ``max_requests``); returns the final :meth:`QBAServer.stats`."""
    seen = 0
    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        seen += 1
        try:
            req = decode_request_line(line)
            server.submit(req)
        except ValueError as e:
            rid = "<undecoded>"
            try:
                rid = str(json.loads(line).get("request_id", rid))
            except (json.JSONDecodeError, AttributeError):
                pass
            _emit_jsonl(out_stream, [EvalResult.failure(rid, str(e))])
        else:
            _emit_jsonl(out_stream, server.pump())
        if max_requests is not None and seen >= max_requests:
            break
    _emit_jsonl(out_stream, server.flush())
    return server.stats()


# Queue layout + atomicity helpers live in the jax-free
# qba_tpu.serve.queuefs so the fleet front-end shares them without
# importing the engine; re-exported names keep existing callers working.
_write_json = write_json_atomic
_result_path = _result_path_for


def _reclaim_stale(
    paths: dict[str, str],
    attempts: dict[str, int],
    live: set[str],
    timeout_s: float,
    max_reclaims: int,
    emit,
) -> int:
    """Crash recovery for the file-queue claim protocol: a worker that
    died mid-request leaves its claim file in ``claimed/`` with no
    result — this moves such stale claims back to ``inbox/`` so any
    consumer can retry them.

    Bounds (so one poison request can't loop forever): the k-th reclaim
    of a file requires age ``timeout_s * 2**k`` (exponential backoff —
    a request that keeps killing workers is retried at 1x, 2x, 4x...),
    and after ``max_reclaims`` attempts the file is dead-lettered to
    ``dead/`` with a structured error result in the outbox.  ``live``
    names this process's own in-progress claims, which are never stale.

    Age is measured from the claim file's mtime, which every consumer
    re-stamps to the claim instant right after the claim rename (the
    rename alone would preserve the producer's enqueue-time mtime, and
    inbox wait must not count toward claim staleness — N replicas
    share this directory, and a backlogged request older than the
    timeout would otherwise be stolen from its live claimant the
    moment it was claimed).
    """
    reclaimed = 0
    now = time.time()
    try:
        names = sorted(
            n for n in os.listdir(paths["claimed"]) if n.endswith(".json")
        )
    except OSError:
        return 0
    for name in names:
        if name in live:
            continue
        path = os.path.join(paths["claimed"], name)
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue  # raced away
        k = attempts.get(name, 0)
        if k >= max_reclaims:
            try:
                # qba-protocol: dead-letter
                os.replace(path, os.path.join(paths["dead"], name))
            except OSError:
                continue
            emit([
                EvalResult.failure(
                    os.path.splitext(name)[0],
                    f"dead-lettered after {k} reclaim attempts without a "
                    "result (every claimant died mid-request)",
                )
            ])
            continue
        if age < timeout_s * (2 ** k):
            continue
        try:
            # qba-protocol: reclaim
            os.replace(path, os.path.join(paths["inbox"], name))
        except OSError:
            continue
        attempts[name] = k + 1
        reclaimed += 1
    return reclaimed


def serve_file_queue(
    server: QBAServer,
    queue_dir: str,
    *,
    poll_s: float = WORKER_POLL_S,
    max_requests: int | None = None,
    reclaim_timeout_s: float | None = None,
    max_reclaims: int = MAX_RECLAIMS,
) -> dict[str, Any]:
    """Drive ``server`` from ``queue_dir`` until the ``stop`` sentinel
    appears (or ``max_requests`` requests have been consumed); returns
    the final stats (also written to ``summary.json``).

    Claim lifecycle: ``inbox/ -> claimed/`` (atomic rename at claim)
    ``-> done/`` once the request's result lands in the outbox.  With
    ``reclaim_timeout_s`` set, claims older than the (exponentially
    backed-off) timeout that belong to no live consumer are pushed back
    to the inbox — crash recovery for a worker killed mid-request —
    with at most ``max_reclaims`` retries before dead-lettering
    (:func:`_reclaim_stale`)."""
    paths = queue_paths(queue_dir)
    for key in ("inbox", "claimed", "done", "dead", "outbox"):
        os.makedirs(paths[key], exist_ok=True)

    # request_id -> this process's claim file awaiting its result.
    claim_of: dict[str, str] = {}
    reclaim_attempts: dict[str, int] = {}
    reclaimed_total = 0

    # Fleet workers (replica_id set) heartbeat their lifecycle phase at
    # every transition so the supervisor can tell busy from hung and
    # blame a crash on the in-flight request (docs/SERVING.md
    # "Self-healing").  The writer lives in jax-free queuefs and also
    # rides along on the server for the dispatch/readback phases.
    hb = None
    flight = None
    if server.replica_id is not None:
        hb = HeartbeatWriter(queue_dir, server.replica_id)
        server.heartbeat = hb
        hb.beat("idle")
        # The flight recorder rides beside the heartbeat: a bounded
        # ring of recent lifecycle events, flushed atomically on every
        # note, so a worker that dies without warning (SIGKILL, poison
        # os._exit) leaves its last moments on disk for the
        # supervisor's KI-9 crash report.
        flight = FlightRecorder(queue_dir, server.replica_id)
        server.flight = flight
        flight.note("boot", queue_dir=queue_dir)
    crash_token = os.environ.get(CRASH_HOOK_ENV)

    def settle(name: str) -> None:
        try:
            # qba-protocol: settle
            os.replace(
                os.path.join(paths["claimed"], name),
                os.path.join(paths["done"], name),
            )
        except OSError:
            pass  # already moved (e.g. reclaimed by a peer); result wins

    def emit(results: Iterable[EvalResult]) -> None:
        for res in results:
            _write_json(_result_path(paths["outbox"], res.request_id), res.to_json())
            if flight is not None:
                flight.note(
                    "emit", request_id=res.request_id,
                    trace_id=res.trace_id,
                    outcome="error" if res.error else "ok",
                )
            name = claim_of.pop(res.request_id, None)
            if name is not None:
                settle(name)

    seen = 0
    try:
        while True:
            if reclaim_timeout_s is not None:
                round_reclaimed = _reclaim_stale(
                    paths, reclaim_attempts, set(claim_of.values()),
                    reclaim_timeout_s, max_reclaims, emit,
                )
                reclaimed_total += round_reclaimed
                if round_reclaimed and flight is not None:
                    flight.note("reclaim", count=round_reclaimed)
            names = sorted(
                n for n in os.listdir(paths["inbox"]) if n.endswith(".json")
            )
            # Work-sharing watermark: one pipeline-full of queued trials
            # per consumer.  Past it, serve what we hold before claiming
            # more — the flush window is when peer replicas sharing this
            # queue dir claim the rest of the inbox.  A lone consumer
            # still drains everything, a watermark's worth at a time.
            prefetch = max(1, server.depth) * server.scheduler.chunk_trials
            for name in names:
                if server.backlog_trials >= prefetch:
                    emit(server.flush())
                claimed = os.path.join(paths["claimed"], name)
                try:
                    # qba-protocol: claim
                    os.replace(os.path.join(paths["inbox"], name), claimed)
                except OSError:
                    continue  # another consumer claimed it
                seen += 1
                # The request file's mtime is its enqueue time
                # (producers write via temp + rename, and the rename
                # into claimed/ preserves it) — so claim time minus
                # mtime IS the queue wait, attributed separately from
                # device time on the result.  Capture it, then stamp
                # claim time onto the file: peers judge claim
                # staleness by this same mtime, and without the
                # re-stamp a request that waited longer than the
                # reclaim timeout in the inbox would look stale the
                # instant it was claimed and be stolen from its live
                # claimant (re-executed, then dead-lettered).
                claim_t = time.time()
                try:
                    queue_wait = max(
                        0.0, claim_t - os.path.getmtime(claimed)
                    )
                except OSError:
                    queue_wait = None
                try:
                    # qba-protocol: restamp
                    os.utime(claimed, (claim_t, claim_t))
                except OSError:
                    pass  # raced away; the eventual result still wins
                # The claim-phase heartbeat names the file slug BEFORE
                # decode: if this worker dies anywhere past this point
                # (decode, submit, dispatch), the supervisor knows
                # which request to blame.
                if hb is not None:
                    hb.beat("claim", [os.path.splitext(name)[0]])
                if flight is not None:
                    flight.note(
                        "claim", request_slug=os.path.splitext(name)[0],
                        queue_wait_s=queue_wait,
                    )
                try:
                    with open(claimed) as f:
                        req = decode_request_line(f.read())
                    if crash_token and crash_token in req.request_id:
                        # Test-only poison hook: die like a compile OOM
                        # would — no cleanup, no result, claim left in
                        # claimed/ for the supervisor to attribute.
                        os._exit(CRASH_HOOK_EXIT)
                    server.submit(req, queue_wait_s=queue_wait)
                except ValueError as e:
                    emit([EvalResult.failure(os.path.splitext(name)[0], str(e))])
                    settle(name)
                else:
                    claim_of[req.request_id] = name
                    emit(server.pump())
                if max_requests is not None and seen >= max_requests:
                    emit(server.flush())
                    return _finish(server, paths, reclaimed_total)
            if os.path.exists(paths["stop"]):
                emit(server.flush())
                return _finish(server, paths, reclaimed_total)
            if not names:
                # Quiet inbox: flush stragglers in partial chunks so a
                # lone request is never stuck behind an unfilled chunk.
                if server.busy:
                    emit(server.flush())
                if hb is not None:
                    hb.beat("idle")
                time.sleep(poll_s)
    finally:
        emit(server.flush())


def _finish(
    server: QBAServer, paths: dict[str, str], reclaimed: int = 0
) -> dict[str, Any]:
    stats = server.stats()
    stats["reclaimed"] = reclaimed
    path = paths["summary"]
    if server.replica_id is not None:
        # One summary file per replica: N pool workers sharing a queue
        # directory must not clobber each other's exit summaries —
        # fleet_summary() aggregates the per-replica files.
        path = os.path.join(
            os.path.dirname(path),
            f"summary-{request_slug(server.replica_id)}.json",
        )
    _write_json(path, stats)
    return stats
