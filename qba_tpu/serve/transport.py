"""Network-free transports for the evaluation service.

Tier-1 runs in hermetic CI containers, so the server speaks two
filesystem/pipe protocols instead of sockets:

* **jsonl** — one :class:`EvalRequest` JSON object per stdin line, one
  :class:`EvalResult` JSON object per stdout line (completion order),
  run summary on stderr at EOF.  Composes with shell pipes:
  ``cat requests.jsonl | qba-tpu serve --transport jsonl > results.jsonl``.
* **file-queue** — a queue directory with ``inbox/`` (drop
  ``*.json`` request files; the server claims them atomically by
  rename into ``claimed/``), ``outbox/`` (one result file per request,
  written via temp-file + rename so readers never see partial JSON),
  and a ``stop`` sentinel file that triggers drain + ``summary.json``
  + clean exit.  This is the transport the CI smoke step and
  examples/load_gen.py drive.

Both transports keep the stream flowing on bad input: a malformed or
invalid request becomes an error :class:`EvalResult`, never a server
crash.  Batching policy: requests are pumped as they arrive (full
chunks dispatch immediately); a partial chunk is flushed when the
input goes quiet (EOF on jsonl, an empty poll on file-queue), so tail
requests never wait on traffic that isn't coming.
"""

from __future__ import annotations

import json
import os
import time
from typing import IO, Any, Iterable

from qba_tpu.serve.engine import QBAServer
from qba_tpu.serve.request import EvalResult, decode_request_line


def _emit_jsonl(out: IO[str], results: Iterable[EvalResult]) -> int:
    n = 0
    for res in results:
        out.write(json.dumps(res.to_json()) + "\n")
        n += 1
    if n:
        out.flush()
    return n


def serve_jsonl(
    server: QBAServer,
    in_stream: IO[str],
    out_stream: IO[str],
    *,
    max_requests: int | None = None,
) -> dict[str, Any]:
    """Drive ``server`` from a JSONL stream until EOF (or
    ``max_requests``); returns the final :meth:`QBAServer.stats`."""
    seen = 0
    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        seen += 1
        try:
            req = decode_request_line(line)
            server.submit(req)
        except ValueError as e:
            rid = "<undecoded>"
            try:
                rid = str(json.loads(line).get("request_id", rid))
            except (json.JSONDecodeError, AttributeError):
                pass
            _emit_jsonl(out_stream, [EvalResult.failure(rid, str(e))])
        else:
            _emit_jsonl(out_stream, server.pump())
        if max_requests is not None and seen >= max_requests:
            break
    _emit_jsonl(out_stream, server.flush())
    return server.stats()


def queue_paths(queue_dir: str) -> dict[str, str]:
    return {
        "inbox": os.path.join(queue_dir, "inbox"),
        "claimed": os.path.join(queue_dir, "claimed"),
        "outbox": os.path.join(queue_dir, "outbox"),
        "stop": os.path.join(queue_dir, "stop"),
        "summary": os.path.join(queue_dir, "summary.json"),
    }


def _write_json(path: str, payload: dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)


def _result_path(outbox: str, request_id: str) -> str:
    slug = "".join(
        c if c.isalnum() or c in "-_." else "_" for c in request_id
    ) or "request"
    return os.path.join(outbox, slug + ".json")


def serve_file_queue(
    server: QBAServer,
    queue_dir: str,
    *,
    poll_s: float = 0.05,
    max_requests: int | None = None,
) -> dict[str, Any]:
    """Drive ``server`` from ``queue_dir`` until the ``stop`` sentinel
    appears (or ``max_requests`` requests have been consumed); returns
    the final stats (also written to ``summary.json``)."""
    paths = queue_paths(queue_dir)
    for key in ("inbox", "claimed", "outbox"):
        os.makedirs(paths[key], exist_ok=True)

    def emit(results: Iterable[EvalResult]) -> None:
        for res in results:
            _write_json(_result_path(paths["outbox"], res.request_id), res.to_json())

    seen = 0
    try:
        while True:
            names = sorted(
                n for n in os.listdir(paths["inbox"]) if n.endswith(".json")
            )
            for name in names:
                claimed = os.path.join(paths["claimed"], name)
                try:
                    os.replace(os.path.join(paths["inbox"], name), claimed)
                except OSError:
                    continue  # another consumer claimed it
                seen += 1
                try:
                    with open(claimed) as f:
                        req = decode_request_line(f.read())
                    server.submit(req)
                except ValueError as e:
                    emit([EvalResult.failure(os.path.splitext(name)[0], str(e))])
                else:
                    emit(server.pump())
                if max_requests is not None and seen >= max_requests:
                    emit(server.flush())
                    return _finish(server, paths)
            if os.path.exists(paths["stop"]):
                emit(server.flush())
                return _finish(server, paths)
            if not names:
                # Quiet inbox: flush stragglers in partial chunks so a
                # lone request is never stuck behind an unfilled chunk.
                if server.busy:
                    emit(server.flush())
                time.sleep(poll_s)
    finally:
        emit(server.flush())


def _finish(server: QBAServer, paths: dict[str, str]) -> dict[str, Any]:
    stats = server.stats()
    _write_json(paths["summary"], stats)
    return stats
