"""File-queue layout and atomic-write helpers (jax-free by design).

The file-queue transport (:mod:`qba_tpu.serve.transport`) and the fleet
front-end (:mod:`qba_tpu.serve.fleet.frontend`) share one on-disk
protocol: requests are dropped into ``inbox/`` and claimed by atomic
rename into ``claimed/``, results land in ``outbox/`` via temp-file +
rename, and a ``stop`` sentinel triggers drain.  This module owns the
path layout and the two atomicity helpers so both sides agree on them
without the front-end importing the engine — the asyncio front-end
must stay importable (and provably, see
:func:`qba_tpu.analysis.transfers.check_fleet`) with no jax and no
device values in the process.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any

from qba_tpu.serve.timing import IDLE_REBEAT_S


def queue_paths(queue_dir: str) -> dict[str, str]:
    return {
        "inbox": os.path.join(queue_dir, "inbox"),
        "claimed": os.path.join(queue_dir, "claimed"),
        "done": os.path.join(queue_dir, "done"),
        "dead": os.path.join(queue_dir, "dead"),
        "outbox": os.path.join(queue_dir, "outbox"),
        "consumed": os.path.join(queue_dir, "consumed"),
        "stop": os.path.join(queue_dir, "stop"),
        "summary": os.path.join(queue_dir, "summary.json"),
        "crash_ledger": os.path.join(queue_dir, "crash_ledger.json"),
    }


def write_json_atomic(path: str, payload: dict[str, Any]) -> None:
    """Temp-file + rename: a concurrent reader sees the old file or the
    new one, never a partial write.  The temp name is writer-unique so
    concurrent writers of the same path don't interleave into one temp
    file before their renames."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    # qba-protocol: publish
    os.replace(tmp, path)


#: Longest id that may map to itself; longer ones are truncated and
#: hash-suffixed so two ids differing only past this point still get
#: distinct (and filesystem-legal, NAME_MAX-safe) queue filenames.
_SLUG_MAX = 100


def request_slug(request_id: str) -> str:
    """Filesystem-safe **injective** slug for a request id (shared by
    result files and per-request telemetry directories).

    A short id that is already filesystem-safe maps to itself;
    anything else maps to its sanitized (and truncated) form plus a
    short hash of the raw id.  Injectivity matters because distinct
    client-supplied ids must never share a queue filename — ``'a/b'``
    and ``'a_b'`` colliding would overwrite one request's inbox file
    with the other's and resolve both pending futures from a single
    result.  The hash suffix is joined with ``~``, a character the
    sanitizer never passes through, so a literal id crafted to look
    like ``<sanitized>~<digest>`` cannot collide with a hashed slug:
    self-mapped slugs never contain ``~``, hashed ones always do.
    """
    safe = "".join(
        c if c.isalnum() or c in "-_." else "_" for c in request_id
    )
    if safe == request_id and safe and len(safe) <= _SLUG_MAX:
        return safe
    digest = hashlib.sha1(
        request_id.encode("utf-8", "surrogatepass")
    ).hexdigest()[:10]
    return f"{safe[:_SLUG_MAX] or 'request'}~{digest}"


def result_path(outbox: str, request_id: str) -> str:
    return os.path.join(outbox, request_slug(request_id) + ".json")


def inbox_request_path(inbox: str, request_id: str) -> str:
    return os.path.join(inbox, request_slug(request_id) + ".json")


def drop_request(inbox: str, payload: dict[str, Any], request_id: str) -> str:
    """Write one request file into the inbox atomically; returns the
    path.  This is the producer half of the claim protocol — the
    rename guarantees a consumer never reads partial JSON."""
    path = inbox_request_path(inbox, request_id)
    write_json_atomic(path, payload)
    return path


# ---------------------------------------------------------------------------
# Worker heartbeats (the self-healing layer's observation channel).

HEARTBEAT_SCHEMA = "qba-tpu/heartbeat/v1"

#: Lifecycle phases a worker reports, in rough hot-loop order.  The
#: supervisor's watchdog is phase-aware: ``compile`` legitimately runs
#: orders of magnitude longer than the others (cold XLA compiles), so
#: a long compile is "busy", not "hung".
HEARTBEAT_PHASES = ("idle", "claim", "compile", "dispatch", "readback")


def heartbeat_path(queue_dir: str, replica_id: str) -> str:
    return os.path.join(
        queue_dir, f"heartbeat-{request_slug(replica_id)}.json"
    )


class HeartbeatWriter:
    """Atomic-rename heartbeat file for one file-queue worker.

    Written by the *worker side only* (transport claim loop + server
    dispatch/readback transitions) — the supervisor and the rest of the
    fleet front half may read heartbeats but never write them, which
    :func:`qba_tpu.analysis.transfers.check_fleet` proves statically.
    Like everything in this module the writer is jax-free by
    construction: a heartbeat write can never sync a device, so beating
    inside the dispatch hot loop costs one small ``os.replace``.

    The stamp is ``time.monotonic()`` (CLOCK_MONOTONIC is machine-wide
    on Linux, so the supervisor process can age it against its own
    monotonic clock without wall-time step hazards).  ``seq`` increases
    on every write as a second staleness witness.  Idle re-beats are
    throttled to ``idle_rebeat_s`` so a quiet worker refreshes its
    liveness without hammering the queue dir every poll tick.
    """

    def __init__(
        self,
        queue_dir: str,
        replica_id: str,
        *,
        idle_rebeat_s: float = IDLE_REBEAT_S,
    ) -> None:
        self.path = heartbeat_path(queue_dir, replica_id)
        self.replica_id = replica_id
        self.idle_rebeat_s = idle_rebeat_s
        self.seq = 0
        self._last_phase: str | None = None
        self._last_write = 0.0

    def beat(self, phase: str, request_ids: tuple[str, ...] | list[str] = ()) -> bool:
        """Record a phase transition; returns True if a file write
        happened (idle->idle re-beats inside the throttle window are
        skipped — the previous stamp is still fresh)."""
        if phase not in HEARTBEAT_PHASES:
            raise ValueError(
                f"unknown heartbeat phase {phase!r}; one of {HEARTBEAT_PHASES}"
            )
        now = time.monotonic()
        if (
            phase == "idle"
            and self._last_phase == "idle"
            and now - self._last_write < self.idle_rebeat_s
        ):
            return False
        self.seq += 1
        payload = {
            "schema": HEARTBEAT_SCHEMA,
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "seq": self.seq,
            "phase": phase,
            "request_ids": list(request_ids),
            "monotonic": now,
            "stamp": time.time(),
        }
        try:
            write_json_atomic(self.path, payload)
        except OSError:
            return False  # a missing queue dir must never kill the worker
        self._last_phase = phase
        self._last_write = now
        return True


def read_heartbeat(queue_dir: str, replica_id: str) -> dict[str, Any] | None:
    """The last heartbeat one replica wrote, or None (never booted far
    enough to beat, or the file is mid-rename — atomic writes mean a
    readable file is always complete)."""
    try:
        with open(heartbeat_path(queue_dir, replica_id)) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


def heartbeat_ages(queue_dir: str) -> dict[str, float]:
    """Per-replica heartbeat staleness in seconds: monotonic now minus
    the replica's last stamp.  Scans ``heartbeat-*.json`` so the
    frontend can report staleness with no supervisor attached (the
    ``GET /status`` satellite) and the metrics collector can gauge it
    at scrape time — both read-only, no new sockets."""
    ages: dict[str, float] = {}
    now = time.monotonic()
    try:
        names = os.listdir(queue_dir)
    except OSError:
        return ages
    for name in names:
        if not (name.startswith("heartbeat-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(queue_dir, name)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(payload, dict):
            continue
        rid = payload.get("replica_id")
        stamp = payload.get("monotonic")
        if isinstance(rid, str) and isinstance(stamp, (int, float)):
            ages[rid] = max(0.0, now - float(stamp))
    return ages


# ---------------------------------------------------------------------------
# Flight recorder (KI-9's execution-history channel, docs/OBSERVABILITY.md).

FLIGHT_SCHEMA = "qba-tpu/flight-recorder/v1"

#: Ring capacity: enough to hold a full request's lifecycle transitions
#: several times over, small enough that every flush is one tiny atomic
#: rename beside the heartbeat.
FLIGHT_CAPACITY = 64


def flight_path(queue_dir: str, replica_id: str) -> str:
    return os.path.join(
        queue_dir, f"flight-{request_slug(replica_id)}.json"
    )


class FlightRecorder:
    """Bounded ring of recent structured worker events, flushed
    atomically beside the heartbeat on every note.

    Same write discipline as the heartbeat — worker side only, atomic
    rename, jax-free, and a missing queue dir never kills the worker.
    The flush-per-note policy is the point: the recorder exists for the
    moment the worker dies *without warning* (SIGKILL, poison
    ``os._exit``), so the on-disk tail must always be current.  The
    supervisor embeds the tail into KI-9 ``crash_report``s, showing
    what the worker was doing when it died.
    """

    def __init__(
        self,
        queue_dir: str,
        replica_id: str,
        *,
        capacity: int = FLIGHT_CAPACITY,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = flight_path(queue_dir, replica_id)
        self.replica_id = replica_id
        self.capacity = capacity
        self.events: list[dict[str, Any]] = []
        self.seq = 0

    def note(self, event: str, **fields: Any) -> dict[str, Any]:
        """Append one event (wall + monotonic stamped) and flush."""
        self.seq += 1
        rec = {
            "seq": self.seq,
            "event": event,
            "monotonic": time.monotonic(),
            "stamp": time.time(),
            **fields,
        }
        self.events.append(rec)
        if len(self.events) > self.capacity:
            del self.events[: len(self.events) - self.capacity]
        try:
            write_json_atomic(self.path, {
                "schema": FLIGHT_SCHEMA,
                "replica_id": self.replica_id,
                "pid": os.getpid(),
                "capacity": self.capacity,
                "events": self.events,
            })
        except OSError:
            pass  # same contract as the heartbeat writer
        return rec


def read_flight_recorder(
    queue_dir: str, replica_id: str, *, tail: int | None = None
) -> dict[str, Any] | None:
    """The replica's flight-recorder file (optionally truncated to the
    last ``tail`` events), or None if it never recorded."""
    try:
        with open(flight_path(queue_dir, replica_id)) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    if tail is not None and isinstance(payload.get("events"), list):
        payload = {**payload, "events": payload["events"][-tail:]}
    return payload
