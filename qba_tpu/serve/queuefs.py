"""File-queue layout and atomic-write helpers (jax-free by design).

The file-queue transport (:mod:`qba_tpu.serve.transport`) and the fleet
front-end (:mod:`qba_tpu.serve.fleet.frontend`) share one on-disk
protocol: requests are dropped into ``inbox/`` and claimed by atomic
rename into ``claimed/``, results land in ``outbox/`` via temp-file +
rename, and a ``stop`` sentinel triggers drain.  This module owns the
path layout and the two atomicity helpers so both sides agree on them
without the front-end importing the engine — the asyncio front-end
must stay importable (and provably, see
:func:`qba_tpu.analysis.transfers.check_fleet`) with no jax and no
device values in the process.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any


def queue_paths(queue_dir: str) -> dict[str, str]:
    return {
        "inbox": os.path.join(queue_dir, "inbox"),
        "claimed": os.path.join(queue_dir, "claimed"),
        "done": os.path.join(queue_dir, "done"),
        "dead": os.path.join(queue_dir, "dead"),
        "outbox": os.path.join(queue_dir, "outbox"),
        "consumed": os.path.join(queue_dir, "consumed"),
        "stop": os.path.join(queue_dir, "stop"),
        "summary": os.path.join(queue_dir, "summary.json"),
    }


def write_json_atomic(path: str, payload: dict[str, Any]) -> None:
    """Temp-file + rename: a concurrent reader sees the old file or the
    new one, never a partial write.  The temp name is writer-unique so
    concurrent writers of the same path don't interleave into one temp
    file before their renames."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)


def request_slug(request_id: str) -> str:
    """Filesystem-safe **injective** slug for a request id (shared by
    result files and per-request telemetry directories).

    An id that is already filesystem-safe maps to itself; anything
    else maps to its sanitized form plus a short hash of the raw id.
    Injectivity matters because distinct client-supplied ids must
    never share a queue filename — ``'a/b'`` and ``'a_b'`` colliding
    would overwrite one request's inbox file with the other's and
    resolve both pending futures from a single result.
    """
    safe = "".join(
        c if c.isalnum() or c in "-_." else "_" for c in request_id
    )
    if safe == request_id and safe:
        return safe
    digest = hashlib.sha1(
        request_id.encode("utf-8", "surrogatepass")
    ).hexdigest()[:10]
    return f"{safe or 'request'}-{digest}"


def result_path(outbox: str, request_id: str) -> str:
    return os.path.join(outbox, request_slug(request_id) + ".json")


def inbox_request_path(inbox: str, request_id: str) -> str:
    return os.path.join(inbox, request_slug(request_id) + ".json")


def drop_request(inbox: str, payload: dict[str, Any], request_id: str) -> str:
    """Write one request file into the inbox atomically; returns the
    path.  This is the producer half of the claim protocol — the
    rename guarantees a consumer never reads partial JSON."""
    path = inbox_request_path(inbox, request_id)
    write_json_atomic(path, payload)
    return path
