"""The long-lived evaluation engine: double-buffered chunk dispatch.

Life of a request (docs/SERVING.md has the diagram):

1. **submit** — the request's config is validated, its trial keys are
   derived exactly as a direct run would
   (:func:`~qba_tpu.backends.jax_backend.trial_keys` recipe: split of
   ``jax.random.key(seed)``), and the key material is queued under the
   request's shape bucket.  A per-request :class:`SpanRecorder` opens
   the ``request`` root span here — the latency clock starts at
   arrival, not at dispatch.
2. **dispatch** — full chunks go to the device via
   :func:`~qba_tpu.backends.jax_backend.run_trials` on the bucket
   config.  Dispatch is asynchronous (the span around it measures
   enqueue only, and is deliberately NOT fenced).
3. **readback** — with ``depth`` chunks in flight, the host reads back
   the *trailing* chunk while the device computes the newer ones — the
   sweep.py overlap pattern promoted to the serving loop.  The readback
   span is fenced (device-attributable, docs/PERF.md).
4. **finish** — when a request's last trial lands, its root span
   closes: that duration IS the reported latency, and the server's
   p50/p99 summary (:func:`~qba_tpu.obs.telemetry.span_latency_summary`)
   aggregates exactly those spans.  Each request also gets a full
   validated run manifest.

Warm start: given a ``cache_dir`` the server points JAX's persistent
compilation cache at ``<cache_dir>/xla`` and restores the resolver
plans from ``<cache_dir>/plans.json`` at boot, saving them back on
every flush — a second boot dispatches known shapes with zero compile
probes and zero resolve misses (tests/test_serve.py pins this).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any

import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.obs.manifest import (
    collect_manifest,
    probe_stats_snapshot,
    validate_manifest,
    write_manifest,
)
from qba_tpu.obs.telemetry import Span, SpanRecorder, span_latency_summary
from qba_tpu.serve import persist
from qba_tpu.serve.request import EvalRequest, EvalResult
from qba_tpu.serve.scheduler import BucketScheduler, Chunk, bucket_label

# The per-request root span name; the latency summary keys on it.
REQUEST_SPAN = "request"


@dataclasses.dataclass
class _Active:
    """Server-side state of one in-progress request."""

    req: EvalRequest
    cfg: QBAConfig
    bucket: QBAConfig
    recorder: SpanRecorder
    root_ctx: Any  # open context manager of the root span
    root_span: Span
    probe_before: dict[str, int]
    success: np.ndarray
    overflow: np.ndarray
    arrived: float = 0.0  # time.monotonic() at submit
    deadline_s: float | None = None  # resolved wall-clock budget
    queue_wait_s: float | None = None  # transport wait before submit
    decisions: np.ndarray | None = None  # allocated at first readback
    filled: int = 0
    chunks: int = 0
    # Precision-targeted requests: the parsed target and its live
    # stopping rule.  Sound on the segment stream because the FIFO
    # cursor fills each request's trials as a contiguous prefix — the
    # rule sees exactly the trials [0, filled), in order.
    target: Any = None  # qba_tpu.stats.Target | None
    rule: Any = None  # live stopping rule | None
    # Device early-finish (docs/STATS.md "Device-resident stopping"):
    # "device" requests bypass the bucket scheduler and run their whole
    # targeted budget as ONE on-device while_loop; key_data holds the
    # request's full key table until that dispatch.
    dispatch: str = "host"
    key_data: np.ndarray | None = None

    @property
    def overdue(self) -> bool:
        return (
            self.deadline_s is not None
            and time.monotonic() - self.arrived > self.deadline_s
        )


class QBAServer:
    """Persistent evaluation engine.  Single-threaded by design: one
    recorder per request keeps span nesting well-formed, and the
    overlap comes from JAX's async dispatch, not host threads."""

    def __init__(
        self,
        *,
        chunk_trials: int = 64,
        depth: int = 2,
        telemetry_dir: str | None = None,
        cache_dir: str | None = None,
        warm_start: bool = True,
        deadline_s: float | None = None,
        replica_id: str | None = None,
        dispatch: str = "host",
    ) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if dispatch not in ("host", "device"):
            raise ValueError(
                f"dispatch must be 'host' or 'device', got {dispatch!r}"
            )
        self.scheduler = BucketScheduler(chunk_trials)
        self.depth = depth
        self.deadline_s = deadline_s
        # "device": precision-targeted requests run their whole budget
        # as a single on-device while_loop (stopping predicate compiled
        # in, docs/STATS.md) instead of riding the per-chunk bucket
        # stream.  Untargeted requests — and targeted ones that need
        # per-trial decisions or are smaller than one chunk — still
        # take the host path on a device server.
        self.dispatch = dispatch
        self._device_pending: list[str] = []
        # Fleet attribution: set when this server is one worker of a
        # replica pool — stamped on every result, manifest, and request
        # span so cross-replica aggregation can tell the workers apart.
        self.replica_id = replica_id
        self._expired = 0
        # Set by the file-queue transport when this server is a fleet
        # worker: a jax-free queuefs.HeartbeatWriter that stamps the
        # lifecycle phase (compile/dispatch/readback here; idle/claim in
        # the transport loop) for the supervisor's watchdog.
        self.heartbeat = None
        # Also transport-set: a queuefs.FlightRecorder ring, flushed
        # atomically beside the heartbeat on every note — the crash
        # evidence the supervisor embeds in KI-9 crash reports.
        self.flight = None
        self.telemetry_dir = telemetry_dir
        self.cache_dir = cache_dir
        self.recorder = SpanRecorder()  # server-level chunk spans
        self.restored_plans = 0
        self._active: dict[str, _Active] = {}
        self._in_flight: list[tuple[Chunk, Any]] = []
        self._bucket_decisions: dict[QBAConfig, list[dict]] = {}
        self._served_buckets: list[QBAConfig] = []
        self._request_spans: list[Span] = []
        self._completed = 0
        if cache_dir is not None:
            from qba_tpu.compile_cache import enable_compile_cache, xla_cache_dir

            enable_compile_cache(xla_cache_dir(cache_dir))
            if warm_start:
                self.restored_plans = persist.load_plans(cache_dir)

    # ---- intake ------------------------------------------------------
    def submit(
        self, req: EvalRequest, *, queue_wait_s: float | None = None
    ) -> None:
        """Validate and queue one request (the latency clock starts
        here).  ``queue_wait_s`` is the transport-measured wait before
        this submit (file-queue: claim time minus inbox mtime) — echoed
        on the result for queue-wait vs device-time attribution.
        Raises ``ValueError`` on a bad config or duplicate id —
        transports turn that into an error result."""
        if req.request_id in self._active:
            raise ValueError(f"request id already in flight: {req.request_id!r}")
        cfg = req.config()
        target = rule = None
        if req.target is not None:
            from qba_tpu.stats import parse_target

            # Parse errors surface here, at intake, as the same
            # ValueError-to-error-result path a bad config takes.
            target = parse_target(req.target)
            rule = target.make_rule()
        import jax

        # Intake key derivation: a tiny CPU-resident key table
        # materialized before anything is in flight — nothing
        # device-side exists yet for this request to stall.
        # qba-lint: sync-ok (pre-dispatch host key derivation)
        key_data = np.asarray(
            jax.random.key_data(jax.random.split(jax.random.key(cfg.seed), cfg.trials)),
            dtype=np.uint32,
        )
        recorder = SpanRecorder()
        probe_before = probe_stats_snapshot()
        bucket = self.scheduler.bucket_for(cfg)
        span_args: dict[str, Any] = dict(
            request_id=req.request_id,
            bucket=bucket_label(bucket),
            trials=cfg.trials,
            # Wall-clock anchor: SpanRecorder time is perf_counter
            # seconds, meaningless across processes.  The stitcher
            # (qba_tpu.obs.tracing) shifts this file's spans onto the
            # epoch axis by t0_epoch - root.t0.
            t0_epoch=time.time(),
        )
        if req.trace_id is not None:
            # Adopt — never re-mint (KI-12) — the trace id that rode
            # the queue file from the frontend/campaign minting site.
            span_args["trace_id"] = req.trace_id
        if req.parent_span_id is not None:
            span_args["parent_span_id"] = req.parent_span_id
        if self.replica_id is not None:
            span_args["replica_id"] = self.replica_id
        if queue_wait_s is not None:
            span_args["queue_wait_s"] = queue_wait_s
        # Device early-finish eligibility: a targeted request with at
        # least one whole chunk of budget and no per-trial decision
        # payload.  Everything else falls back to the host bucket
        # stream even on a device server (docs/SERVING.md).
        device_mode = (
            self.dispatch == "device"
            and target is not None
            and not req.return_decisions
            and cfg.trials >= self.scheduler.chunk_trials
        )
        if device_mode:
            span_args["dispatch"] = "device"
        root_ctx = recorder.span(REQUEST_SPAN, cat="serve", **span_args)
        root_span = root_ctx.__enter__()
        if device_mode:
            self._device_pending.append(req.request_id)
        else:
            self.scheduler.enqueue(req.request_id, cfg, key_data)
        if bucket not in self._served_buckets:
            self._served_buckets.append(bucket)
        self._active[req.request_id] = _Active(
            req=req,
            cfg=cfg,
            bucket=bucket,
            recorder=recorder,
            root_ctx=root_ctx,
            root_span=root_span,
            probe_before=probe_before,
            success=np.zeros(cfg.trials, dtype=bool),
            overflow=np.zeros(cfg.trials, dtype=bool),
            arrived=time.monotonic(),
            deadline_s=(
                req.deadline_s if req.deadline_s is not None
                else self.deadline_s
            ),
            queue_wait_s=queue_wait_s,
            target=target,
            rule=rule,
            dispatch="device" if device_mode else "host",
            key_data=key_data if device_mode else None,
        )
        if self.flight is not None:
            self.flight.note(
                "submit", request_id=req.request_id,
                trace_id=req.trace_id, bucket=span_args["bucket"],
                trials=cfg.trials,
            )

    # ---- dispatch / drain --------------------------------------------
    def pump(self) -> list[EvalResult]:
        """Dispatch every *full* chunk, draining as the double buffer
        fills; returns requests completed along the way.  Partial
        chunks wait for more same-bucket traffic until :meth:`flush`."""
        done: list[EvalResult] = self.expire_overdue()
        done.extend(self._pump_device())
        while self.scheduler.has_full_chunk():
            chunk = self.scheduler.next_chunk()
            assert chunk is not None
            done.extend(self._dispatch(chunk))
        return done

    def flush(self) -> list[EvalResult]:
        """Dispatch all pending trials (padding partial chunks), drain
        every in-flight chunk, and persist the resolver plans."""
        done: list[EvalResult] = self.expire_overdue()
        done.extend(self._pump_device())
        while True:
            chunk = self.scheduler.next_chunk()
            if chunk is None:
                break
            done.extend(self._dispatch(chunk))
        while self._in_flight:
            done.extend(self._drain_one())
        if self.cache_dir is not None:
            persist.save_plans(self.cache_dir, self._served_buckets)
        return done

    def expire_overdue(self) -> list[EvalResult]:
        """Turn every request past its wall-clock deadline into a
        structured error result NOW — still-queued trials are cancelled,
        in-flight ones compute but their readback segments are
        discarded.  The stream never wedges behind one slow request:
        this runs at the head of every :meth:`pump`/:meth:`flush`."""
        overdue = [ar for ar in self._active.values() if ar.overdue]
        return [self._expire(ar) for ar in overdue]

    def _expire(self, ar: _Active) -> EvalResult:
        self.scheduler.cancel(ar.req.request_id)
        del self._active[ar.req.request_id]
        ar.root_ctx.__exit__(None, None, None)
        self._request_spans.append(ar.root_span)
        self._expired += 1
        latency = float(ar.root_span.dur or 0.0)
        label = bucket_label(ar.bucket)
        from qba_tpu.stats.estimators import rate_estimate

        k_part = int(ar.success[: ar.filled].sum())
        stats_block: dict[str, Any] = {
            "success_rate": rate_estimate(k_part, ar.filled).to_json(),
            "trials_requested": ar.cfg.trials,
            "trials_completed": ar.filled,
        }
        if ar.target is not None:
            stats_block["target"] = ar.target.to_json()
            stats_block["stop"] = None  # the deadline fired, not the rule
        # The error result still carries the full validated manifest —
        # the caller learns which engine/plan the request WAS bound to
        # and how far it got, not just that it timed out.
        manifest = validate_manifest(
            collect_manifest(
                ar.cfg,
                command="serve",
                decisions=self._bucket_decisions.get(ar.bucket, []),
                probe_stats_before=ar.probe_before,
                spans=ar.recorder,
                extra={
                    "request_id": ar.req.request_id,
                    "bucket": label,
                    "latency_s": latency,
                    "chunks": ar.chunks,
                    "restored_plans": self.restored_plans,
                    "expired": True,
                    "trials_completed": ar.filled,
                    "stats": stats_block,
                    **self._attribution(ar),
                },
            )
        )
        if self.telemetry_dir is not None:
            self._write_telemetry(ar, manifest)
        res = EvalResult.failure(
            ar.req.request_id,
            f"deadline exceeded: {ar.deadline_s}s wall clock, "
            f"{ar.filled}/{ar.cfg.trials} trials complete",
        )
        res.latency_s = latency
        res.bucket = label
        res.chunks = ar.chunks
        res.manifest = manifest
        res.replica_id = self.replica_id
        res.queue_wait_s = ar.queue_wait_s
        res.trace_id = ar.req.trace_id
        if ar.rule is not None and ar.filled:
            # Partial-progress estimate for a timed-out targeted
            # request: anytime-valid over the prefix it did complete.
            res.ci = ar.rule.estimate().to_json()
        return res

    def close(self) -> list[EvalResult]:
        return self.flush()

    def _attribution(self, ar: _Active) -> dict[str, Any]:
        """Fleet attribution fields for a request's manifest extra."""
        out: dict[str, Any] = {}
        if self.replica_id is not None:
            out["replica_id"] = self.replica_id
        if ar.queue_wait_s is not None:
            out["queue_wait_s"] = ar.queue_wait_s
        return out

    @property
    def busy(self) -> bool:
        """True while any trial is queued or any chunk is in flight."""
        return (
            bool(self._in_flight)
            or bool(self._device_pending)
            or self.scheduler.pending_trials() > 0
        )

    @property
    def backlog_trials(self) -> int:
        """Trials accepted but not yet read back: queued in the
        scheduler plus in-flight chunks (chunks are fixed-size, padded)
        plus device-pending targeted budgets.  The file-queue transport
        uses this as its work-sharing watermark — claim more only while
        the pipeline has room."""
        device_pending = sum(
            self._active[rid].cfg.trials
            for rid in self._device_pending
            if rid in self._active
        )
        return (
            self.scheduler.pending_trials()
            + len(self._in_flight) * self.scheduler.chunk_trials
            + device_pending
        )

    # ---- device early-finish -----------------------------------------
    def _pump_device(self) -> list[EvalResult]:
        """Run every device-pending targeted request to its stop chunk,
        one single-dispatch while_loop each (requests already expired by
        the deadline sweep are skipped — their ids are simply gone from
        the active table)."""
        done: list[EvalResult] = []
        pending, self._device_pending = self._device_pending, []
        for rid in pending:
            ar = self._active.get(rid)
            if ar is not None:
                done.append(self._run_device(ar))
        return done

    def _run_device(self, ar: _Active) -> EvalResult:
        """One targeted request as ONE dispatch: the stopping predicate
        rides the on-device while_loop (qba_tpu.sweep._device_loop_prefix)
        over the request's own prefix key table, so the device decides
        when to stop and the host reads back counts + per-trial success
        bits exactly once.  The budget is floor-quantized to whole
        chunks (``trials // chunk_trials`` — docs/SERVING.md); the host
        replay of the per-chunk counts through the request's rule
        produces the same StopDecision the host segment stream would
        have reached at that chunk boundary."""
        import jax
        import jax.numpy as jnp

        from qba_tpu.diagnostics import record_decisions, warn_and_record
        from qba_tpu.diagnostics import QBAWarning
        from qba_tpu.stats.device import stop_tables
        from qba_tpu.sweep import (
            _device_carry_prefix,
            _device_loop_prefix,
        )

        ct = self.scheduler.chunk_trials
        n_chunks = ar.cfg.trials // ct
        label = bucket_label(ar.bucket)
        assert ar.key_data is not None
        keys = jax.random.wrap_key_data(
            jnp.asarray(ar.key_data[: n_chunks * ct])
        )
        lo, hi = stop_tables(ar.target, n_chunks, ct)
        carry = _device_carry_prefix(n_chunks, ct)
        if self.heartbeat is not None:
            self.heartbeat.beat(
                "compile"
                if ar.bucket not in self._bucket_decisions
                else "dispatch",
                [ar.req.request_id],
            )
        span_args = dict(
            bucket=label, budget_chunks=n_chunks, chunk_trials=ct,
        )
        first = ar.bucket not in self._bucket_decisions
        with record_decisions() as decisions:
            with ar.recorder.span(
                "serve.device_loop", cat="serve", **span_args
            ) as sp:
                i_stop, _, counts, ovf, succ = _device_loop_prefix(
                    ar.bucket, n_chunks, ct, carry,
                    jnp.asarray(lo), jnp.asarray(hi), keys,
                )
                # The single loop-level readback barrier of the whole
                # request — the device already decided where to stop.
                i_stop = int(i_stop)
                counts_h = np.asarray(counts)
                ovf_h = np.asarray(ovf)
                succ_h = np.asarray(succ)
                sp.fenced = True
        if first:
            self._bucket_decisions[ar.bucket] = list(decisions)
        dec = None
        for c in range(i_stop):
            ar.success[c * ct : (c + 1) * ct] = succ_h[c * ct : (c + 1) * ct]
            ar.overflow[c * ct : (c + 1) * ct] = bool(ovf_h[c])
            ar.filled += ct
            ar.chunks += 1
            ar.rule.observe(int(counts_h[c]), ct)
            dec = ar.rule.decision()
            if dec is not None:
                break
        # A decision landing exactly on the final budget chunk is
        # consistent: the loop exits on i == n_chunks either way.
        if ar.chunks != i_stop or (dec is None and i_stop < n_chunks):
            warn_and_record(
                "serve device stop diverged from the host rule: device "
                f"stopped after {i_stop} chunks, host replay after "
                f"{ar.chunks}",
                QBAWarning,
                site="serve._run_device",
                device_stop=i_stop,
                host_stop=ar.chunks,
            )
        return self._finish(
            ar, stop=dec if dec is not None else ar.rule.exhausted()
        )

    def _dispatch(self, chunk: Chunk) -> list[EvalResult]:
        import jax
        import jax.numpy as jnp

        from qba_tpu.backends.jax_backend import run_trials
        from qba_tpu.diagnostics import record_decisions

        keys = jax.random.wrap_key_data(jnp.asarray(chunk.key_data))
        label = bucket_label(chunk.bucket)
        span_args = dict(
            bucket=label, chunk=chunk.index, trials=chunk.used,
            padded=self.scheduler.chunk_trials - chunk.used,
        )
        if self.heartbeat is not None:
            # First dispatch of a bucket may trigger a cold XLA compile
            # (minutes, not milliseconds) — beat the distinct "compile"
            # phase so the supervisor's watchdog grants it more rope.
            self.heartbeat.beat(
                "compile"
                if chunk.bucket not in self._bucket_decisions
                else "dispatch",
                sorted({seg.request_id for seg in chunk.segments}),
            )
        if self.flight is not None:
            self.flight.note(
                "compile"
                if chunk.bucket not in self._bucket_decisions
                else "dispatch",
                bucket=label, chunk=chunk.index,
                request_ids=sorted({seg.request_id for seg in chunk.segments}),
            )
        if chunk.bucket not in self._bucket_decisions:
            # First dispatch of this bucket: capture the live resolver
            # decisions so every request served from it can carry them
            # in its manifest (later dispatches hit the memo silently).
            with record_decisions() as decisions:
                with self.recorder.span("serve.dispatch", cat="serve", **span_args):
                    mc = run_trials(chunk.bucket, keys)
            self._bucket_decisions[chunk.bucket] = list(decisions)
        else:
            with self.recorder.span("serve.dispatch", cat="serve", **span_args):
                mc = run_trials(chunk.bucket, keys)
        self._in_flight.append((chunk, mc))
        done: list[EvalResult] = []
        # Double buffer: keep up to depth-1 newer chunks computing on
        # the device while the oldest one is read back on the host.
        while len(self._in_flight) > self.depth - 1:
            done.extend(self._drain_one())
        return done

    def _drain_one(self) -> list[EvalResult]:
        chunk, mc = self._in_flight.pop(0)
        label = bucket_label(chunk.bucket)
        if self.heartbeat is not None:
            self.heartbeat.beat(
                "readback", sorted({seg.request_id for seg in chunk.segments})
            )
        if self.flight is not None:
            self.flight.note(
                "readback", bucket=label, chunk=chunk.index,
                request_ids=sorted({seg.request_id for seg in chunk.segments}),
            )
        with self.recorder.span(
            "serve.readback", cat="serve", bucket=label, chunk=chunk.index
        ) as sp:
            success = np.asarray(mc.trials.success)
            decisions = np.asarray(mc.trials.decisions)
            overflow = np.asarray(mc.trials.overflow)
            # np.asarray IS a host readback — this span measured device
            # completion of everything enqueued up to this chunk.
            sp.fenced = True
        done: list[EvalResult] = []
        for seg in chunk.segments:
            ar = self._active.get(seg.request_id)
            if ar is None:
                # Request expired (deadline) between dispatch and
                # readback — its computed rows are discarded.
                continue
            with ar.recorder.span(
                "serve.chunk", cat="serve",
                chunk=chunk.index, trials=seg.length, bucket=label,
            ):
                if ar.decisions is None:
                    ar.decisions = np.zeros(
                        (ar.cfg.trials,) + decisions.shape[1:], decisions.dtype
                    )
                dst = slice(seg.req_start, seg.req_start + seg.length)
                src = slice(seg.chunk_start, seg.chunk_start + seg.length)
                ar.success[dst] = success[src]
                ar.decisions[dst] = decisions[src]
                ar.overflow[dst] = overflow[src]
            ar.filled += seg.length
            ar.chunks += 1
            if ar.rule is not None:
                # The segment extended the request's contiguous prefix
                # to [0, filled) — chunk counts feed the anytime-valid
                # rule in trial order, so consulting it after every
                # segment keeps the stated error rates.
                ar.rule.observe(int(success[src].sum()), seg.length)
            if ar.filled == ar.cfg.trials:
                done.append(self._finish(ar))
            elif (
                ar.rule is not None and (dec := ar.rule.decision()) is not None
            ):
                # Resolved early: cancel the still-queued trials and
                # answer now with the partial prefix + stop decision
                # (in-flight rows for this request drain to nowhere,
                # same as the deadline path).
                self.scheduler.cancel(ar.req.request_id)
                done.append(self._finish(ar, stop=dec))
        return done

    def _finish(self, ar: _Active, stop=None) -> EvalResult:
        """Close a request: complete (``filled == trials``) or resolved
        early by its precision target (``stop`` from the rule).  The
        result covers exactly the contiguous prefix ``[0, filled)``, so
        a targeted result is bit-identical to the same prefix of the
        untargeted run."""
        from qba_tpu.benchmark import engine_description
        from qba_tpu.stats.estimators import rate_estimate
        from qba_tpu.stats.estimators import success_rate as _success_rate

        if ar.rule is not None and stop is None:
            # Targeted request that filled its whole trial budget: the
            # rule either fired exactly at the end or reports
            # budget_exhausted with the CI actually achieved.
            dec = ar.rule.decision()
            stop = dec if dec is not None else ar.rule.exhausted()
        del self._active[ar.req.request_id]
        ar.root_ctx.__exit__(None, None, None)
        self._request_spans.append(ar.root_span)
        self._completed += 1
        latency = float(ar.root_span.dur or 0.0)
        label = bucket_label(ar.bucket)
        n_done = ar.filled
        k_done = int(ar.success[:n_done].sum())
        # Every serve manifest carries a certified rate (KI-8): point
        # estimate + CI, never a bare number.
        stats_block: dict[str, Any] = {
            "success_rate": rate_estimate(k_done, n_done).to_json(),
            "trials_requested": ar.cfg.trials,
            "trials_completed": n_done,
        }
        if ar.target is not None:
            stats_block["target"] = ar.target.to_json()
            stats_block["stop"] = stop.to_json() if stop is not None else None
        if ar.dispatch == "device":
            # Distinguish the single-dispatch loop from the host chunk
            # stream in the manifest (docs/OBSERVABILITY.md).
            stats_block["dispatch"] = "device"
        manifest = validate_manifest(
            collect_manifest(
                ar.cfg,
                command="serve",
                decisions=self._bucket_decisions.get(ar.bucket, []),
                probe_stats_before=ar.probe_before,
                spans=ar.recorder,
                extra={
                    "request_id": ar.req.request_id,
                    "bucket": label,
                    "latency_s": latency,
                    "chunks": ar.chunks,
                    "restored_plans": self.restored_plans,
                    "stats": stats_block,
                    **self._attribution(ar),
                },
            )
        )
        if self.telemetry_dir is not None:
            self._write_telemetry(ar, manifest)
        if self.flight is not None:
            self.flight.note(
                "finish", request_id=ar.req.request_id,
                trace_id=ar.req.trace_id, latency_s=latency,
            )
        # The device loop reduces on device and never materializes
        # per-trial decisions — its eligibility gate already excluded
        # return_decisions requests.
        assert ar.decisions is not None or not ar.req.return_decisions
        return EvalResult(
            request_id=ar.req.request_id,
            n_trials=n_done,
            successes=k_done,
            success_rate=_success_rate(k_done, n_done),
            any_overflow=bool(ar.overflow[:n_done].any()),
            latency_s=latency,
            engine=engine_description(ar.cfg),
            bucket=label,
            chunks=ar.chunks,
            success=[bool(x) for x in ar.success[:n_done]],
            decisions=(
                ar.decisions[:n_done].tolist()
                if ar.req.return_decisions and ar.decisions is not None
                else None
            ),
            manifest=manifest,
            stop=stop.to_json() if stop is not None else None,
            ci=(
                stop.estimate.to_json()
                if stop is not None and stop.estimate is not None
                else None
            ),
            replica_id=self.replica_id,
            queue_wait_s=ar.queue_wait_s,
            trace_id=ar.req.trace_id,
        )

    def _write_telemetry(self, ar: _Active, manifest: dict) -> None:
        slug = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in ar.req.request_id
        ) or "request"
        directory = os.path.join(self.telemetry_dir or ".", slug)
        os.makedirs(directory, exist_ok=True)
        write_manifest(os.path.join(directory, "run_manifest.json"), manifest)
        ar.recorder.write_jsonl(os.path.join(directory, "spans.jsonl"))
        ar.recorder.write_chrome_trace(os.path.join(directory, "trace.json"))

    # ---- reporting ---------------------------------------------------
    def latency_summary(
        self, percentiles: tuple[float, ...] = (50.0, 99.0)
    ) -> dict[str, Any]:
        """p50/p99 (etc.) over completed requests, computed from the
        closed ``request`` spans themselves."""
        return span_latency_summary(
            self._request_spans, REQUEST_SPAN, percentiles
        )

    def queue_wait_summary(
        self, percentiles: tuple[float, ...] = (50.0, 99.0)
    ) -> dict[str, Any]:
        """Distribution of transport queue waits across finished
        requests (the ``queue_wait_s`` arg the transports stamp on each
        ``request`` span) — the other half of the latency attribution:
        ``latency`` is replica-side time, this is time spent waiting
        for a replica."""
        from qba_tpu.obs.telemetry import _percentile

        waits = sorted(
            float(sp.args["queue_wait_s"])
            for sp in self._request_spans
            if "queue_wait_s" in sp.args
        )
        summary: dict[str, Any] = {"count": len(waits)}
        if not waits:
            return summary
        summary["mean_s"] = sum(waits) / len(waits)
        summary["max_s"] = waits[-1]
        for q in percentiles:
            summary[f"p{q:g}_s"] = _percentile(waits, q)
        return summary

    def stats(self) -> dict[str, Any]:
        from qba_tpu.ops.round_kernel_tiled import resolve_cache_info

        return {
            "replica_id": self.replica_id,
            "dispatch": self.dispatch,
            "completed": self._completed,
            "expired": self._expired,
            "in_flight_chunks": len(self._in_flight),
            "pending_trials": self.scheduler.pending_trials(),
            "buckets": [bucket_label(b) for b in self._served_buckets],
            "restored_plans": self.restored_plans,
            "latency": self.latency_summary(),
            "queue_wait": self.queue_wait_summary(),
            "resolver": resolve_cache_info(),
        }


def serve_batch(server: QBAServer, requests: list[EvalRequest]) -> list[EvalResult]:
    """Convenience in-process driver: submit everything, pump as full
    chunks form, flush at the end.  Bad requests become error results;
    result order is completion order (error results appear at the point
    of rejection)."""
    results: list[EvalResult] = []
    for req in requests:
        try:
            server.submit(req)
        except (ValueError, TypeError) as e:
            rid = getattr(req, "request_id", "<unknown>")
            results.append(EvalResult.failure(str(rid), str(e)))
            continue
        results.extend(server.pump())
    results.extend(server.flush())
    return results
