"""Single source of truth for the fleet protocol's timing constants.

Every number that shapes the file-queue concurrency protocol — poll
periods, the watchdog budget and its phase scale, the reclaim ladder,
the poison threshold, the breaker window, respawn backoff — lives
here and ONLY here.  Three consumers import these values:

* the shipped code (:mod:`qba_tpu.serve.transport`,
  :mod:`qba_tpu.serve.fleet.supervisor`,
  :mod:`qba_tpu.serve.fleet.pool`, the CLI argparse defaults), so the
  running fleet and its ``--help`` text can never disagree;
* the KI-10 protocol model (:mod:`qba_tpu.analysis.protocol`), so the
  model checker's bounds (reclaim attempts, poison deaths) are the
  shipped bounds, not a copy that drifts;
* docs/SERVING.md, whose prose cites this module instead of repeating
  the literals.

Jax-free by design like the rest of the fleet front half
(:func:`qba_tpu.analysis.transfers.check_fleet` imports through it).
"""

from __future__ import annotations

# ---- worker claim loop (serve/transport.py) -------------------------------

#: File-queue inbox poll period for one serve worker (``--poll-s``).
WORKER_POLL_S = 0.05

#: Base stale-claim timeout (``--reclaim-timeout-s``): the k-th reclaim
#: of a claim file requires age ``RECLAIM_TIMEOUT_S * 2**k`` measured
#: from the claim-instant mtime re-stamp (never from enqueue time —
#: the PR 12 race, re-proven by the KI-10 model on every lint).
RECLAIM_TIMEOUT_S = 5.0

#: Reclaim attempts before a request is dead-lettered (``--max-reclaims``).
MAX_RECLAIMS = 3

#: Idle heartbeat re-beat throttle (queuefs.HeartbeatWriter).
IDLE_REBEAT_S = 1.0

# ---- supervisor (serve/fleet/supervisor.py) -------------------------------

#: Supervision loop period: one :meth:`FleetSupervisor.poll` per this
#: many seconds.  A dead worker's claim is released within ONE such
#: poll (a KI-10 model invariant), so this bounds re-serve latency.
SUPERVISOR_POLL_S = 0.5

#: Base heartbeat-staleness budget before a worker is "hung"
#: (``--watchdog-s``).
WATCHDOG_S = 10.0

#: Multiplier on :data:`WATCHDOG_S` per heartbeat phase.  Cold XLA
#: compiles legitimately run orders of magnitude longer than a dispatch
#: or readback; every phase not listed gets the base budget.
WATCHDOG_PHASE_SCALE = {"compile": 30.0}

#: Boot grace = this many watchdog budgets before a beat-less fresh pid
#: is "hung" (workers importing jax take seconds to boot).
BOOT_GRACE_SCALE = 3.0

#: Worker deaths blamed on one request before it is quarantined as
#: poison (``--poison-threshold``): one poison request costs at most
#: this many workers — the KI-10 model checks exactly that bound.
POISON_THRESHOLD = 2

#: Crash-loop breaker: this many deaths of one slot inside
#: :data:`BREAKER_WINDOW_S` benches it (``--breaker-k``).
BREAKER_K = 3
BREAKER_WINDOW_S = 60.0

# ---- replica pool (serve/fleet/pool.py) -----------------------------------

#: Respawns of one slot before it is benched for good (``--max-respawns``).
MAX_RESPAWNS = 5

#: The k-th respawn of a slot waits ``RESPAWN_BACKOFF_S * 2**(k-1)``
#: after the previous one (``--respawn-backoff-s``).
RESPAWN_BACKOFF_S = 0.5

# ---- fleet front-end (serve/fleet/frontend.py) ----------------------------

#: Outbox poll period for the front-end's result watcher.
FRONTEND_POLL_S = 0.02
