"""qba_tpu.serve — persistent QBA evaluation service.

The serving subsystem (ROADMAP item 3): a long-lived engine process
that answers :class:`EvalRequest` s ("n parties, d traitors — failure
probability at sizeL=L?") by bucketing mixed-shape traffic onto the
memoized kernel plans, double-buffering device chunks against host
readback, and emitting one validated run manifest + span tree per
request.  See docs/SERVING.md.

Module map:

* :mod:`~qba_tpu.serve.request` — wire model (EvalRequest/EvalResult).
* :mod:`~qba_tpu.serve.scheduler` — shape buckets, chunk packing.
* :mod:`~qba_tpu.serve.engine` — :class:`QBAServer`, the dispatch loop.
* :mod:`~qba_tpu.serve.transport` — stdin-JSONL and file-queue drivers.
* :mod:`~qba_tpu.serve.persist` — the ``plans.json`` warm-start artifact.
* :mod:`~qba_tpu.serve.queuefs` — jax-free file-queue path helpers.
* :mod:`~qba_tpu.serve.fleet` — network front-end, replica pool, and
  target-aware admission (ROADMAP item 4); imported lazily by callers,
  not here, so the jax-free fleet front half stays importable without
  the engine.
"""

from qba_tpu.serve.engine import QBAServer, serve_batch
from qba_tpu.serve.persist import load_plans, save_plans, saved_configs
from qba_tpu.serve.request import EvalRequest, EvalResult
from qba_tpu.serve.scheduler import BucketScheduler, bucket_config, bucket_label
from qba_tpu.serve.transport import serve_file_queue, serve_jsonl

__all__ = [
    "QBAServer",
    "serve_batch",
    "EvalRequest",
    "EvalResult",
    "BucketScheduler",
    "bucket_config",
    "bucket_label",
    "serve_jsonl",
    "serve_file_queue",
    "load_plans",
    "save_plans",
    "saved_configs",
]
