"""qba_tpu.serve.fleet — network front-end, replica pool, admission.

The fleet subsystem turns the single-process :class:`QBAServer` into a
multi-replica service without inventing new dispatch machinery:

* :mod:`frontend` — an asyncio socket/HTTP JSONL listener (never
  imports jax; analysis/transfers.py proves it) that writes admitted
  requests into the crash-hardened file queue and streams results back;
* :mod:`pool` — N worker processes, each running the existing
  ``qba-tpu serve --transport file-queue`` loop pinned to one device,
  booting from the shared warm-start artifact behind its file lock;
* :mod:`admission` — target-aware pricing of each request against the
  KI-2 trial-ceiling model and a fleet-wide capacity window, with
  typed admit/defer/reject decisions and release-on-settle;
* :mod:`summary` — cross-replica aggregation: per-replica and
  fleet-wide p50/p99, queue-wait vs device-time attribution, admission
  decision counts, one ``fleet_summary.json``;
* :mod:`supervisor` — the self-healing loop: phase-aware heartbeat
  watchdog (SIGKILLs hung workers), blame-attributed crash ledger,
  poison-request quarantine, and a crash-loop breaker that benches
  flapping replicas and releases their admission capacity.

``qba-tpu fleet`` (cli.py) wires all four together; docs/SERVING.md
has the topology and operator guide.
"""

from qba_tpu.serve.fleet.admission import (
    ADMIT,
    DEFER,
    REASONS,
    REJECT,
    AdmissionController,
    AdmissionDecision,
)
from qba_tpu.serve.fleet.frontend import FleetFrontend
from qba_tpu.serve.fleet.pool import (
    Replica,
    ReplicaPool,
    make_device_env,
    tpu_present,
)
from qba_tpu.serve.fleet.summary import (
    FLEET_SUMMARY_SCHEMA,
    fleet_summary,
    merge_fleet_spans,
    write_fleet_summary,
)
from qba_tpu.serve.fleet.supervisor import (
    CRASH_LEDGER_SCHEMA,
    WATCHDOG_PHASE_SCALE,
    FleetSupervisor,
)

__all__ = [
    "ADMIT",
    "DEFER",
    "REJECT",
    "REASONS",
    "AdmissionController",
    "AdmissionDecision",
    "FleetFrontend",
    "Replica",
    "ReplicaPool",
    "make_device_env",
    "tpu_present",
    "FLEET_SUMMARY_SCHEMA",
    "fleet_summary",
    "merge_fleet_spans",
    "write_fleet_summary",
    "CRASH_LEDGER_SCHEMA",
    "WATCHDOG_PHASE_SCALE",
    "FleetSupervisor",
]
