"""The replica pool: N worker processes over one shared file queue.

Each replica is a separate OS process running the *existing*, proven
serve loop — ``qba-tpu serve --transport file-queue --replica-id rK``
(:func:`qba_tpu.serve.transport.serve_file_queue` driving a
:class:`~qba_tpu.serve.engine.QBAServer`) — against the shared queue
directory.  The pool process itself never touches a device; all the
multi-process machinery rides on protocols that already exist:

* **work distribution** — the inbox claim is an atomic rename, so N
  pollers never double-serve a request;
* **fault story** — a replica killed mid-request leaves a stale claim
  that any *surviving* replica reclaims (``--reclaim-timeout-s``), so
  ``kill -9`` loses zero requests (tests/test_fleet.py, CI fleet job);
* **warm start** — every replica boots from the shared cache dir; the
  artifact lock (:mod:`qba_tpu.serve.persist`) keeps concurrent
  save/load merges torn-free, and the merged union makes the second
  fleet boot zero-probe on all replicas;
* **device placement** — per-replica environment: on CPU each worker
  is its own jax process; on TPU ``make_device_env`` pins replica K to
  chip K (``TPU_VISIBLE_CHIPS``) so the pool is a dp slice of the
  8-device mesh, one chip per worker, no mesh config needed.

The pool writes ``replicas.json`` (pids + env) into the queue dir so
out-of-process chaos drivers (examples/load_gen.py ``--chaos-kill``)
can pick a victim.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any

from qba_tpu.serve.queuefs import queue_paths, request_slug, write_json_atomic
from qba_tpu.serve.timing import (
    MAX_RECLAIMS,
    MAX_RESPAWNS,
    RECLAIM_TIMEOUT_S,
    RESPAWN_BACKOFF_S,
    WORKER_POLL_S,
)


def tpu_present() -> bool:
    """Best-effort, jax-free TPU detection for hosts where
    ``JAX_PLATFORMS`` is unset (the common case — jax auto-detects the
    platform, so operators rarely export it).  Checks the TPU runtime
    env vars the launchers set, then the libtpu install, then the
    accelerator device nodes.  Must never import jax: the pool process
    stays device-free (:func:`qba_tpu.analysis.transfers.check_fleet`)."""
    tpu_env = (
        "TPU_ACCELERATOR_TYPE",
        "TPU_WORKER_ID",
        "TPU_WORKER_HOSTNAMES",
        "CLOUD_TPU_TASK_ID",
        "TPU_VISIBLE_CHIPS",
    )
    if any(os.environ.get(v) for v in tpu_env):
        return True
    try:
        import importlib.util

        if importlib.util.find_spec("libtpu") is not None:
            return True
    except (ImportError, ValueError):
        pass
    return any(
        os.path.exists(p) for p in ("/dev/accel0", "/dev/vfio/0")
    )


def make_device_env(index: int, platform: str | None = None) -> dict[str, str]:
    """Per-replica environment overrides pinning worker ``index`` to
    one device.  CPU (the CI backend): nothing to pin — each process
    has its own host device.  TPU: ``TPU_VISIBLE_CHIPS`` restricts the
    worker to chip ``index`` and the process-bounds vars tell the
    runtime it owns a 1-chip slice (the standard single-host
    multi-process carve-up).

    With no explicit ``platform`` and no ``JAX_PLATFORMS`` in the
    environment, TPU hardware is auto-detected (:func:`tpu_present`):
    on a real TPU host jax auto-initializes TPU without any env var,
    and falling into the CPU branch there would leave every replica
    grabbing all chips (libtpu is single-process per chip, so replicas
    2..N would die at startup) with CPU thread-cap flags to boot."""
    platform = platform or os.environ.get("JAX_PLATFORMS", "")
    env: dict[str, str] = {}
    if platform:
        env["JAX_PLATFORMS"] = platform
    on_tpu = "tpu" in platform or (not platform and tpu_present())
    if on_tpu:
        env["TPU_VISIBLE_CHIPS"] = str(index)
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
        env["TPU_PROCESS_BOUNDS"] = "1,1,1"
    else:
        # One replica ~= one core: cap XLA's CPU intra-op thread pool
        # so N replicas scale on an N-core host instead of N full-size
        # thread pools fighting over it — the dp-slice analogue of the
        # one-chip-per-worker TPU carve-up above.
        flags = os.environ.get("XLA_FLAGS", "")
        if "intra_op_parallelism_threads" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_cpu_multi_thread_eigen=false"
                " intra_op_parallelism_threads=1"
            ).strip()
    return env


@dataclasses.dataclass
class Replica:
    """One pool worker: its id, process handle, and pinned env."""

    replica_id: str
    proc: subprocess.Popen
    env: dict[str, str]
    returncode: int | None = None

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class ReplicaPool:
    """Spawn, watch, kill, and stop N serve workers on one queue dir."""

    def __init__(
        self,
        queue_dir: str,
        *,
        replicas: int = 2,
        chunk_trials: int = 64,
        depth: int = 2,
        cache_dir: str | None = None,
        telemetry_dir: str | None = None,
        deadline_s: float | None = None,
        reclaim_timeout_s: float | None = RECLAIM_TIMEOUT_S,
        max_reclaims: int = MAX_RECLAIMS,
        poll_s: float = WORKER_POLL_S,
        platform: str | None = None,
        python: str | None = None,
        max_respawns: int = MAX_RESPAWNS,
        respawn_backoff_s: float = RESPAWN_BACKOFF_S,
    ) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.queue_dir = queue_dir
        self.n_replicas = replicas
        self.chunk_trials = chunk_trials
        self.depth = depth
        self.cache_dir = cache_dir
        self.telemetry_dir = telemetry_dir
        self.deadline_s = deadline_s
        self.reclaim_timeout_s = reclaim_timeout_s
        self.max_reclaims = max_reclaims
        self.poll_s = poll_s
        self.platform = platform
        self.python = python or sys.executable
        self.max_respawns = max_respawns
        self.respawn_backoff_s = respawn_backoff_s
        self.replicas: list[Replica] = []
        #: Respawn audit trail: ``{replica_id, at, respawns}`` per
        #: restart (wall-clock timestamp so post-mortems can correlate
        #: with request latencies and the crash ledger).
        self.restarted: list[dict[str, Any]] = []
        #: Slots withdrawn from service: the crash-loop breaker
        #: (supervisor) or the ``max_respawns`` cap benches a replica
        #: id here; :meth:`respawn_dead` never revives a benched slot.
        self.benched: set[str] = set()
        self._respawns: dict[str, int] = {}  # replica_id -> count
        self._next_respawn_at: dict[str, float] = {}  # monotonic gate

    def worker_argv(self, replica_id: str) -> list[str]:
        """The exact serve invocation a replica runs — the file-queue
        loop whose dispatch ordering check_serve_dispatch proves; the
        pool adds no dispatch path of its own."""
        argv = [
            self.python, "-m", "qba_tpu", "serve",
            "--transport", "file-queue",
            "--queue-dir", self.queue_dir,
            "--replica-id", replica_id,
            "--chunk-trials", str(self.chunk_trials),
            "--depth", str(self.depth),
            "--poll-s", str(self.poll_s),
            "--max-reclaims", str(self.max_reclaims),
        ]
        if self.reclaim_timeout_s is not None:
            argv += ["--reclaim-timeout-s", str(self.reclaim_timeout_s)]
        if self.cache_dir is not None:
            argv += ["--cache-dir", self.cache_dir]
        if self.telemetry_dir is not None:
            argv += ["--telemetry", self.telemetry_dir]
        if self.deadline_s is not None:
            argv += ["--deadline-s", str(self.deadline_s)]
        return argv

    def _spawn(self, index: int) -> Replica:
        replica_id = f"r{index}"
        overrides = make_device_env(index, self.platform)
        env = {**os.environ, **overrides}
        # Workers run `-m qba_tpu` from whatever cwd the pool owner has;
        # make the package importable even when it isn't installed and
        # the cwd is not the repo root.
        import qba_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(qba_tpu.__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join([pkg_root] + [p for p in parts if p])
        proc = subprocess.Popen(self.worker_argv(replica_id), env=env)
        return Replica(replica_id=replica_id, proc=proc, env=overrides)

    def start(self) -> list[str]:
        """Boot every replica; returns their ids.  Boot order is not
        serialized — the plans.json artifact lock makes concurrent
        warm starts safe."""
        if self.replicas:
            raise RuntimeError("pool already started")
        os.makedirs(self.queue_dir, exist_ok=True)
        self.replicas = [self._spawn(i) for i in range(self.n_replicas)]
        self._write_state()
        return [r.replica_id for r in self.replicas]

    def _write_state(self) -> None:
        write_json_atomic(
            os.path.join(self.queue_dir, "replicas.json"),
            {
                "replicas": [
                    {
                        "replica_id": r.replica_id,
                        "pid": r.proc.pid,
                        "alive": r.alive,
                        "env": r.env,
                    }
                    for r in self.replicas
                ],
                "restarted": self.restarted,
                "benched": sorted(self.benched),
            },
        )

    def alive(self) -> list[str]:
        return [r.replica_id for r in self.replicas if r.alive]

    def kill(self, replica_id: str, sig: int = signal.SIGKILL) -> int:
        """Chaos hook: send ``sig`` (default ``kill -9``) to one
        replica; returns its pid.  The victim's in-flight claims are
        reclaimed by the survivors."""
        for r in self.replicas:
            if r.replica_id == replica_id and r.alive:
                r.proc.send_signal(sig)
                try:
                    r.proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    # A wedged zombie (e.g. stuck in an uninterruptible
                    # device ioctl) must not raise out of a chaos/
                    # supervisor kill — record what we know and move on.
                    pass
                r.returncode = r.proc.returncode
                self._write_state()
                return r.proc.pid
        raise ValueError(f"no live replica {replica_id!r}")

    def bench(self, replica_id: str) -> bool:
        """Withdraw one slot from service permanently (crash-loop
        breaker): its dead process is never respawned again.  Returns
        True if the slot was newly benched."""
        if replica_id in self.benched:
            return False
        self.benched.add(replica_id)
        self._write_state()
        return True

    def respawn_dead(self) -> list[str]:
        """Replace dead replicas with fresh processes under the same
        id/env slot (the supervision loop for long-lived fleets; chaos
        tests leave this off to prove reclaim alone suffices).

        Guard rails against a bad device becoming a hot respawn loop:
        the k-th respawn of a slot waits ``respawn_backoff_s * 2**(k-1)``
        after the previous one (exponential backoff), and a slot that
        has burned ``max_respawns`` respawns is benched for good.
        Returns the ids actually respawned this call."""
        respawned = []
        now = time.monotonic()
        for i, r in enumerate(self.replicas):
            rid = r.replica_id
            if r.alive or rid in respawned or rid in self.benched:
                continue
            k = self._respawns.get(rid, 0)
            if k >= self.max_respawns:
                self.bench(rid)
                continue
            if now < self._next_respawn_at.get(rid, 0.0):
                continue  # still inside the backoff window
            self.replicas[i] = self._spawn(i)
            self._respawns[rid] = k + 1
            self._next_respawn_at[rid] = (
                now + self.respawn_backoff_s * (2 ** k)
            )
            self.restarted.append(
                {"replica_id": rid, "at": time.time(), "respawns": k + 1}
            )
            respawned.append(rid)
        if respawned:
            self._write_state()
        return respawned

    def stop(self, timeout_s: float = 300.0) -> dict[str, int | None]:
        """Drop the stop sentinel and wait for every live replica to
        drain and exit; returns ``{replica_id: returncode}``."""
        paths = queue_paths(self.queue_dir)
        with open(paths["stop"], "w"):
            pass
        deadline = time.monotonic() + timeout_s
        codes: dict[str, int | None] = {}
        for r in self.replicas:
            budget = max(0.1, deadline - time.monotonic())
            try:
                r.proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                r.proc.kill()
                try:
                    r.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass  # zombie outlived SIGKILL; don't leak the rest
            codes[r.replica_id] = r.proc.returncode
        self._write_state()
        return codes

    def summaries(self) -> dict[str, dict[str, Any]]:
        """Per-replica exit summaries (``summary-<id>.json`` written by
        each worker's serve loop)."""
        out: dict[str, dict[str, Any]] = {}
        for r in self.replicas:
            path = os.path.join(
                self.queue_dir,
                f"summary-{request_slug(r.replica_id)}.json",
            )
            try:
                with open(path) as f:
                    out[r.replica_id] = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
        return out
