"""Target-aware admission: price a request before it touches the queue.

The front-end must not let traffic wedge the pool: a shape whose
per-trial pool footprint exceeds one device's HBM can never execute
(KI-2, :func:`qba_tpu.analysis.memory.trial_ceiling`), and a burst of
huge trial budgets would bury small requests in queue wait.  So every
request is **priced** before it is enqueued:

* the price is the request's trial budget, discounted by its precision
  target when it has one (:meth:`qba_tpu.stats.Target.planning_trials`
  — the Wald expected-sample-size bound, quantized up to whole device
  chunks, since the scheduler dispatches chunk-granular work);
* the controller keeps a live ledger of priced-but-unfinished trials
  against a fleet-wide capacity window (``replicas × window_chunks ×
  chunk_trials`` by default) and **admits**, **defers** (capacity is
  temporarily full — retry after a release), or **rejects** (the
  request can never be served: invalid, unservable shape, or bigger
  than the whole window) with a typed reason;
* :meth:`AdmissionController.settle` releases a request's priced
  capacity when its result lands — an early-stopped target releases
  the *unused* remainder to the next tenant at the same moment.

Determinism contract (tests/test_fleet.py): decisions are a pure
function of the request sequence and settle points — no clocks, no
randomness — so a fixed stream always yields the same decision list.
No jax at module level: pricing pulls the KI-2 ceiling model in lazily
and only for shapes it has not seen before.
"""

from __future__ import annotations

import dataclasses
from typing import Any

#: Decision vocabulary: ``action`` is one of ADMIT/DEFER/REJECT and
#: ``reason`` one of REASONS (typed, so callers can switch on it).
ADMIT = "admit"
DEFER = "defer"
REJECT = "reject"

REASONS = (
    "capacity_available",  # admit: priced trials fit the live window
    "window_full",  # defer: retry once in-flight work settles
    "invalid_request",  # reject: config/target failed validation
    "unservable_shape",  # reject: KI-2 ceiling below one device chunk
    "oversized_request",  # reject: price exceeds the whole fleet window
)


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    """One typed admission verdict, echoed on the wire
    (``EvalResult.admission``) and into the fleet summary."""

    action: str
    reason: str
    request_id: str
    bucket: str = ""
    priced_trials: int = 0
    outstanding_trials: int = 0  # ledger total AFTER this decision
    capacity_trials: int = 0
    detail: str = ""

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class AdmissionController:
    """Live capacity ledger + pricing for one fleet front-end.

    ``capacity_trials`` is the fleet-wide outstanding-work window: how
    many priced trials may be admitted-but-unsettled at once.  The
    default models each replica holding ``window_chunks`` chunks of
    work (its double-buffer depth plus queue headroom); the CLI exposes
    it directly for operators with measured numbers.
    """

    def __init__(
        self,
        *,
        chunk_trials: int = 64,
        replicas: int = 1,
        capacity_trials: int | None = None,
        window_chunks: int = 8,
        hbm_bytes: int | None = None,
        mesh_shape: tuple[int, int] | None = None,
        tp_comms: str = "ring",
    ) -> None:
        if chunk_trials < 1:
            raise ValueError(f"chunk_trials must be >= 1, got {chunk_trials}")
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if mesh_shape is not None:
            dp, tp = mesh_shape
            if dp < 1 or tp < 1:
                raise ValueError(
                    f"mesh_shape must be (dp >= 1, tp >= 1), got {mesh_shape}"
                )
            mesh_shape = (dp, tp)
        if tp_comms not in ("ring", "all_gather"):
            raise ValueError(
                f"unknown tp_comms {tp_comms!r}; expected 'ring' or "
                "'all_gather'"
            )
        self.mesh_shape = mesh_shape
        self.tp_comms = tp_comms
        self.chunk_trials = chunk_trials
        self.replicas = replicas
        self.capacity_trials = (
            capacity_trials
            if capacity_trials is not None
            else replicas * window_chunks * chunk_trials
        )
        self.hbm_bytes = hbm_bytes
        self._outstanding: dict[str, int] = {}  # request_id -> priced
        self._ceilings: dict[str, int] = {}  # bucket label -> KI-2 ceiling
        self.decisions: list[AdmissionDecision] = []
        self.released_trials = 0  # settled price, incl. early-stop refunds
        # Degraded-mode accounting: the supervisor's crash-loop breaker
        # shrinks the window when it benches a replica, so admission
        # keeps pricing against capacity that actually exists.
        self._base_capacity = self.capacity_trials
        self._benched: set[str] = set()
        # Batch-hint state (campaign drivers): request ids whose first
        # batch-mode DEFER is already on the ledger, so re-polls of the
        # same still-deferred id stay silent until it resolves.
        self._batch_deferred: set[str] = set()

    @property
    def outstanding_trials(self) -> int:
        return sum(self._outstanding.values())

    # ---- pricing -----------------------------------------------------
    def _chunk_quantize(self, trials: int) -> int:
        chunks = -(-trials // self.chunk_trials)
        return chunks * self.chunk_trials

    def price(self, req) -> tuple[int, str]:
        """(priced_trials, detail) for one request: the trial budget,
        target-discounted, rounded up to whole device chunks."""
        if req.target is None:
            return self._chunk_quantize(req.trials), "full budget"
        from qba_tpu.stats import parse_target

        target = parse_target(req.target)
        planned = target.planning_trials(req.trials)
        return (
            self._chunk_quantize(planned),
            f"target plans {planned} of {req.trials} budget trials",
        )

    def _ceiling(self, req) -> int:
        """KI-2 trial ceiling for the request's shape bucket, memoized
        per bucket label (the ceiling is pure shape arithmetic).

        On a dp×tp mesh the admissible batch is the SHARDED ceiling
        (:func:`qba_tpu.analysis.memory.sharded_trial_ceiling` at this
        controller's comms transport): a shape whose full pool busts
        one chip may still be servable party-sharded, and conversely
        the comms transient makes the per-device number smaller than
        the naive ``trial_ceiling / tp`` split.  A shape tp does not
        divide falls back to the single-chip price (the scheduler runs
        it unsharded)."""
        from qba_tpu.analysis.memory import (
            HBM_BYTES,
            sharded_trial_ceiling,
            trial_ceiling,
        )
        from qba_tpu.serve.scheduler import bucket_config, bucket_label

        bucket = bucket_config(req.config(), self.chunk_trials)
        label = bucket_label(bucket)
        if label not in self._ceilings:
            hbm = self.hbm_bytes if self.hbm_bytes is not None else HBM_BYTES
            if (
                self.mesh_shape is not None
                and self.mesh_shape[1] > 1
                and bucket.n_lieutenants % self.mesh_shape[1] == 0
            ):
                dp, tp = self.mesh_shape
                self._ceilings[label] = sharded_trial_ceiling(
                    bucket, dp=dp, tp=tp, hbm_bytes=hbm,
                    comms=self.tp_comms,
                )["mesh_trials"]
            else:
                self._ceilings[label] = trial_ceiling(bucket, hbm_bytes=hbm)
        return self._ceilings[label]

    # ---- the decision ------------------------------------------------
    def try_admit(
        self, req, *, record: bool = True, batch: bool = False
    ) -> AdmissionDecision:
        """Price and decide one request.  ``admit`` records the price
        in the ledger (the caller MUST eventually :meth:`settle`);
        ``defer`` and ``reject`` leave the ledger untouched.

        ``record=False`` keeps the decision out of :attr:`decisions`
        (the ledger still mutates on admit) — the front-end's deferred
        retry loop uses it so a still-full re-poll of the deferred
        head doesn't append a DEFER per settle event: the decision
        list stays a pure function of the request stream and settle
        points, not of settle *timing*.  A retry that resolves
        (admit/reject) is recorded by the caller via :meth:`record`.

        ``batch=True`` is the campaign-driver hint (retry contract in
        docs/SERVING.md "Batch admission"): a driver submitting
        hundreds of cells re-offers every still-open cell each round,
        so per-rid only the FIRST ``defer/window_full`` is recorded —
        later re-offers of the same deferred id return the live
        verdict without touching the decision list until the id
        resolves (admit or reject), which is recorded and clears the
        id.  The recorded ledger therefore stays a pure function of
        the distinct request stream and settle points, however many
        times the driver polls.
        """
        rid = req.request_id
        dec = self._evaluate(req)
        if batch and record:
            if dec.action == DEFER:
                if rid in self._batch_deferred:
                    record = False  # re-offer of a recorded defer: silent
                else:
                    self._batch_deferred.add(rid)
            else:
                # The deferred id resolved (admit or reject): record it
                # and forget the defer so a future re-submission of the
                # same id starts fresh.
                self._batch_deferred.discard(rid)
        if record:
            self.decisions.append(dec)
        return dec

    def _evaluate(self, req) -> AdmissionDecision:
        """Price and decide without touching the decision list (the
        ledger of outstanding trials still mutates on admit) — the
        single decision procedure behind plain, retry (``record=
        False``), and batch admission."""
        from qba_tpu.serve.scheduler import bucket_config, bucket_label

        rid = req.request_id
        try:
            label = bucket_label(bucket_config(req.config(), self.chunk_trials))
            ceiling = self._ceiling(req)
            priced, detail = self.price(req)
        except ValueError as e:
            return self._decide(
                REJECT, "invalid_request", rid, detail=str(e), record=False
            )
        if ceiling < self.chunk_trials:
            where = (
                f"the (dp={self.mesh_shape[0]}, tp={self.mesh_shape[1]}) "
                f"mesh under {self.tp_comms} comms"
                if self.mesh_shape is not None
                else "one device"
            )
            return self._decide(
                REJECT, "unservable_shape", rid, bucket=label, priced=priced,
                detail=(
                    f"KI-2 trial ceiling {ceiling} < chunk_trials "
                    f"{self.chunk_trials}: one chunk of this shape "
                    f"exhausts HBM on {where}"
                ),
                record=False,
            )
        if priced > self.capacity_trials:
            return self._decide(
                REJECT, "oversized_request", rid, bucket=label, priced=priced,
                detail=(
                    f"priced {priced} trials > fleet window "
                    f"{self.capacity_trials}: would wedge every other tenant"
                ),
                record=False,
            )
        if self.outstanding_trials + priced > self.capacity_trials:
            return self._decide(
                DEFER, "window_full", rid, bucket=label, priced=priced,
                detail=(
                    f"{self.outstanding_trials} trials outstanding; retry "
                    "after a release"
                ),
                record=False,
            )
        self._outstanding[rid] = priced
        return self._decide(
            ADMIT, "capacity_available", rid, bucket=label, priced=priced,
            detail=detail, record=False,
        )

    def record(self, decision: AdmissionDecision) -> None:
        """Append a decision obtained with ``try_admit(record=False)``
        to the ledger — the retry loop's way of recording only the
        final verdict of a deferred request, not every failed poll."""
        self.decisions.append(decision)

    def bench_replica(self, replica_id: str) -> int:
        """Release one benched replica's share of the capacity window
        (crash-loop breaker, docs/SERVING.md "Self-healing"): with a
        slot permanently out of service, admitting against its share
        would queue requests against phantom capacity.  The share is
        the per-replica slice of the *initial* window; returns the
        trials actually released (0 on a repeat bench of the same id).
        Deterministic like every other decision input: the window is a
        pure function of the bench events, not of time."""
        if replica_id in self._benched:
            return 0
        self._benched.add(replica_id)
        share = min(self._base_capacity // self.replicas, self.capacity_trials)
        self.capacity_trials -= share
        return share

    def settle(self, request_id: str, executed_trials: int | None = None) -> int:
        """Release a finished request's priced capacity; returns the
        trials released.  ``executed_trials`` (from the result) lets
        the summary report how much of the price an early stop
        refunded — the release itself is always the full price, which
        is what makes deferred admits retry-able the moment any
        tenant finishes."""
        priced = self._outstanding.pop(request_id, 0)
        self.released_trials += priced
        return priced

    def _decide(
        self,
        action: str,
        reason: str,
        rid: str,
        *,
        bucket: str = "",
        priced: int = 0,
        detail: str = "",
        record: bool = True,
    ) -> AdmissionDecision:
        assert reason in REASONS, reason
        dec = AdmissionDecision(
            action=action,
            reason=reason,
            request_id=rid,
            bucket=bucket,
            priced_trials=priced,
            outstanding_trials=self.outstanding_trials,
            capacity_trials=self.capacity_trials,
            detail=detail,
        )
        if record:
            self.decisions.append(dec)
        return dec

    def summary(self) -> dict[str, Any]:
        """Decision counts + ledger state for the fleet summary."""
        by_action: dict[str, int] = {}
        by_reason: dict[str, int] = {}
        for dec in self.decisions:
            by_action[dec.action] = by_action.get(dec.action, 0) + 1
            by_reason[dec.reason] = by_reason.get(dec.reason, 0) + 1
        return {
            "decisions": len(self.decisions),
            "by_action": by_action,
            "by_reason": by_reason,
            "capacity_trials": self.capacity_trials,
            "base_capacity_trials": self._base_capacity,
            "benched_replicas": sorted(self._benched),
            "outstanding_trials": self.outstanding_trials,
            "released_trials": self.released_trials,
            "bucket_ceilings": dict(self._ceilings),
            "mesh_shape": (
                list(self.mesh_shape) if self.mesh_shape is not None else None
            ),
            "tp_comms": self.tp_comms,
        }
