"""The fleet front-end: asyncio sockets in, file-queue out.

One asyncio TCP server speaks the existing wire model — one
:class:`~qba_tpu.serve.request.EvalRequest` JSON object per line in,
one :class:`~qba_tpu.serve.request.EvalResult` JSON object per line
out (completion order) — plus a minimal HTTP mode on the same port
(``POST`` any path with a JSONL body answers 200 with the result
lines; ``GET`` answers the live fleet status).  Requests without a
``request_id`` get one assigned here.

The front-end does **no device work** — statically provable
(:func:`qba_tpu.analysis.transfers.check_fleet`): it never imports
jax, and its only job is admission
(:class:`~qba_tpu.serve.fleet.admission.AdmissionController`) plus
moving JSON between sockets and the PR 9 crash-hardened file queue.
Admitted requests are dropped into ``inbox/`` (temp + rename), the
replica pool's claim/reclaim/dead-letter/deadline machinery is the
entire fault story, and a poller watches ``outbox/`` to route each
result back to the connection that asked — after settling its priced
capacity, which is the moment a deferred request gets retried.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import threading
from collections import deque
from typing import Any

from qba_tpu.obs.metrics import MetricsRegistry
from qba_tpu.obs.tracing import TraceEventLog, mint_span_id, mint_trace_id
from qba_tpu.serve.fleet.admission import ADMIT, DEFER, AdmissionController
from qba_tpu.serve.queuefs import (
    drop_request,
    heartbeat_ages,
    queue_paths,
    result_path,
)
from qba_tpu.serve.request import EvalRequest, EvalResult
from qba_tpu.serve.timing import FRONTEND_POLL_S


class FleetFrontend:
    """One listening socket bridging clients to the shared queue."""

    def __init__(
        self,
        queue_dir: str,
        admission: AdmissionController | None = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        poll_s: float = FRONTEND_POLL_S,
        request_prefix: str = "fl",
        max_requests: int | None = None,
        health_provider=None,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        self.queue_dir = queue_dir
        self.paths = queue_paths(queue_dir)
        os.makedirs(self.paths["inbox"], exist_ok=True)
        os.makedirs(self.paths["outbox"], exist_ok=True)
        os.makedirs(self.paths["consumed"], exist_ok=True)
        self.admission = admission
        self.host = host
        self.port = port  # 0 = ephemeral; replaced by the bound port
        self.poll_s = poll_s
        self.max_requests = max_requests
        # Zero-arg callable returning the supervisor's per-replica
        # health map (``FleetSupervisor.health``) for ``GET /status``.
        # A callable, not the supervisor itself: the front-end must not
        # grow a pool/process dependency — and check_fleet keeps
        # proving it device-free either way.
        self.health_provider = health_provider
        # Live metrics plane (docs/OBSERVABILITY.md): push counters at
        # the decision points below, pull point-in-time gauges from the
        # queue dir at scrape time.  ``GET /metrics`` renders this.
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.metrics.add_collector(self._collect_queue_metrics)
        # Lifecycle event log: the frontend is the minting site for
        # trace ids (KI-12 registered site) and stamps intake /
        # admission / settle onto each trace's timeline.
        self.trace_log = TraceEventLog(queue_dir)
        self._trace_ids: dict[str, str] = {}  # rid -> trace_id
        self._ids = itertools.count()
        self._prefix = request_prefix
        self._futures: dict[str, asyncio.Future] = {}
        self._admitted: dict[str, dict[str, Any]] = {}  # rid -> decision json
        self._deferred: deque[EvalRequest] = deque()
        self.requests_seen = 0  # valid requests accepted off sockets
        self.results_forwarded = 0
        self._release = asyncio.Event()
        self._done = asyncio.Event()
        self._server: asyncio.AbstractServer | None = None
        self._tasks: list[asyncio.Task] = []
        self._connections: set[asyncio.Task] = set()
        # Thread-mode plumbing (start_in_thread/stop_in_thread).
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None

    # ---- lifecycle ---------------------------------------------------
    async def start(self) -> None:
        """Bind the socket and start the outbox/admission pollers;
        ``self.port`` holds the actual port after this returns."""
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._tasks = [
            asyncio.ensure_future(self._watch_outbox()),
            asyncio.ensure_future(self._retry_deferred()),
        ]

    async def serve_until_done(self) -> None:
        """Run until :meth:`request_stop` (or ``max_requests`` requests
        have been fully answered), then shut down cleanly."""
        if self._server is None:
            await self.start()
        await self._done.wait()
        await self._shutdown()

    def request_stop(self) -> None:
        self._done.set()

    async def _shutdown(self) -> None:
        assert self._server is not None
        self._server.close()
        await self._server.wait_closed()
        # Let in-flight connection handlers finish writing their last
        # results (wait_closed does not wait for handler coroutines).
        if self._connections:
            await asyncio.wait(self._connections, timeout=30)
        for t in [*self._tasks, *self._connections]:
            t.cancel()
        for t in [*self._tasks, *self._connections]:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    def run(self) -> None:
        """Blocking convenience: serve on a fresh event loop."""
        asyncio.run(self.serve_until_done())

    def start_in_thread(self) -> int:
        """Run the front-end on a daemon thread; returns the bound port
        once the socket is listening (for in-process drivers: tests and
        examples/load_gen.py)."""
        ready = threading.Event()

        def _main() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            loop.run_until_complete(self.start())
            ready.set()
            loop.run_until_complete(self.serve_until_done())
            loop.close()

        self._thread = threading.Thread(target=_main, daemon=True)
        self._thread.start()
        if not ready.wait(timeout=60):
            raise RuntimeError("fleet frontend failed to start listening")
        return self.port

    def stop_in_thread(self, timeout_s: float = 60.0) -> None:
        if self._loop is not None and self._thread is not None:
            try:
                self._loop.call_soon_threadsafe(self._done.set)
            except RuntimeError:
                pass  # loop already closed: max_requests ended the serve
            self._thread.join(timeout=timeout_s)

    # ---- request intake ----------------------------------------------
    def _assign_id(self) -> str:
        while True:
            rid = f"{self._prefix}{next(self._ids):05d}"
            if rid not in self._futures and not os.path.exists(
                result_path(self.paths["outbox"], rid)
            ):
                # The outbox check keeps a restarted front-end (whose
                # counter restarts at fl00000 over the same queue dir)
                # from watching a leftover result file and resolving a
                # fresh request with a stale payload.
                return rid

    def _intake(self, payload: dict[str, Any]) -> tuple[str, asyncio.Future]:
        """Admit one decoded request payload; always returns a future
        that resolves to the result JSON (rejections resolve it
        immediately)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        rid = str(payload.get("request_id") or self._assign_id())
        # Trace context is minted HERE (the one registered fleet-side
        # minting site — KI-12 proves there are no others) before any
        # refusal branch, so even a rejected request gets a closed
        # trace.  A client-supplied trace id is adopted, not replaced:
        # identity travels with the trace.
        trace_id = str(payload.get("trace_id") or mint_trace_id())
        intake_span = str(payload.get("parent_span_id") or mint_span_id())
        payload = {**payload, "request_id": rid, "trace_id": trace_id,
                   "parent_span_id": intake_span}
        self.metrics.inc("qba_intake_requests_total", exemplar=trace_id)
        self.trace_log.emit("intake", trace_id, rid, span_id=intake_span)

        def _refuse(error: str, reason: str,
                    decision_json: dict[str, Any] | None = None) -> None:
            res = EvalResult.failure(rid, error)
            res.trace_id = trace_id
            res.admission = decision_json
            self.trace_log.emit("reject", trace_id, rid, reason=reason)
            self.trace_log.emit("settle", trace_id, rid, outcome="rejected")
            fut.set_result(res.to_json())

        if rid in self._futures:
            _refuse(f"request id already pending: {rid!r}", "duplicate_id")
            return rid, fut
        if os.path.exists(result_path(self.paths["outbox"], rid)):
            # A leftover result under this id (client id reuse, or a
            # previous fleet run over the same queue dir) would resolve
            # this request instantly with the stale payload while the
            # fresh one still executes — refuse instead.
            _refuse(
                f"request id {rid!r} already has a result in the "
                "outbox (id reuse over a live queue dir); pick a "
                "fresh id",
                "stale_result",
            )
            return rid, fut
        try:
            req = EvalRequest.from_json(payload)
        except (ValueError, TypeError) as e:
            _refuse(str(e), "undecodable")
            return rid, fut
        self.requests_seen += 1
        if self.admission is None:
            self._futures[rid] = fut
            self._trace_ids[rid] = trace_id
            self.trace_log.emit("admit", trace_id, rid)
            drop_request(self.paths["inbox"], req.to_json(), rid)
            self._maybe_close_intake()
            return rid, fut
        decision = self.admission.try_admit(req)
        self.metrics.inc(
            "qba_admission_decisions_total",
            labels={"action": str(decision.action),
                    "reason": str(decision.reason or "ok")},
            exemplar=trace_id,
        )
        if decision.action == ADMIT:
            self._futures[rid] = fut
            self._admitted[rid] = decision.to_json()
            self._trace_ids[rid] = trace_id
            self.trace_log.emit("admit", trace_id, rid,
                                reason=decision.reason)
            drop_request(self.paths["inbox"], req.to_json(), rid)
        elif decision.action == DEFER:
            self._futures[rid] = fut
            self._admitted[rid] = decision.to_json()
            self._trace_ids[rid] = trace_id
            self.trace_log.emit("defer", trace_id, rid,
                                reason=decision.reason)
            self._deferred.append(req)
        else:
            _refuse(
                f"rejected: {decision.reason} ({decision.detail})",
                str(decision.reason), decision.to_json(),
            )
        self._maybe_close_intake()
        return rid, fut

    def _maybe_close_intake(self) -> None:
        if (
            self.max_requests is not None
            and self.requests_seen >= self.max_requests
            and not self._futures
            and not self._deferred
        ):
            self._done.set()

    # ---- background pollers ------------------------------------------
    async def _watch_outbox(self) -> None:
        """Route finished results from the outbox back to their
        callers, settling priced capacity as they land."""
        while True:
            landed = []
            for rid in list(self._futures):
                path = result_path(self.paths["outbox"], rid)
                if not os.path.exists(path):
                    continue
                try:
                    with open(path) as f:
                        payload = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue  # racing the writer's rename; next poll wins
                fut = self._futures.pop(rid, None)
                if fut is None or fut.done():
                    continue
                decision = self._admitted.pop(rid, None)
                if decision is not None:
                    payload["admission"] = decision
                if self.admission is not None:
                    self.admission.settle(rid, payload.get("n_trials"))
                    self._release.set()
                self.results_forwarded += 1
                trace_id = self._trace_ids.pop(rid, None) or payload.get(
                    "trace_id"
                )
                outcome = "error" if payload.get("error") else "ok"
                self.metrics.inc("qba_results_forwarded_total",
                                 labels={"outcome": outcome},
                                 exemplar=trace_id)
                for metric, key in (
                    ("qba_request_latency_seconds", "latency_s"),
                    ("qba_request_queue_wait_seconds", "queue_wait_s"),
                ):
                    value = payload.get(key)
                    if isinstance(value, (int, float)):
                        self.metrics.observe(metric, float(value),
                                             exemplar=trace_id)
                self.trace_log.emit("settle", trace_id, rid,
                                    outcome=outcome)
                fut.set_result(payload)
                try:
                    # Consume the result file: a forwarded result left
                    # in outbox/ would answer a future request under a
                    # reused id with this (by then stale) payload, and
                    # the watch loop would keep rereading it.  Moved,
                    # not deleted — fleet_summary() recomputes the
                    # client-experienced latency/queue-wait
                    # distributions from consumed/ + outbox/.
                    # qba-protocol: consume
                    os.replace(
                        path,
                        os.path.join(
                            self.paths["consumed"], os.path.basename(path)
                        ),
                    )
                except OSError:
                    pass
                landed.append(rid)
            if landed:
                self._maybe_close_intake()
            await asyncio.sleep(self.poll_s)

    async def _retry_deferred(self) -> None:
        """Re-run admission for deferred requests (FIFO, head-of-line)
        every time a settle releases capacity."""
        while True:
            await self._release.wait()
            self._release.clear()
            while self._deferred and self.admission is not None:
                req = self._deferred[0]
                # record=False: a still-full retry must not append a
                # DEFER per settle event — the decision ledger stays a
                # pure function of the request stream and settle
                # points, not of settle timing.  Only the retry that
                # resolves (admit or reject) is recorded.
                decision = self.admission.try_admit(req, record=False)
                if decision.action == DEFER:
                    break
                self.admission.record(decision)
                self._deferred.popleft()
                rid = req.request_id
                self._admitted[rid] = decision.to_json()
                trace_id = self._trace_ids.get(rid) or req.trace_id
                self.metrics.inc(
                    "qba_admission_decisions_total",
                    labels={"action": str(decision.action),
                            "reason": str(decision.reason or "ok")},
                    exemplar=trace_id,
                )
                if decision.action == ADMIT:
                    self.trace_log.emit("admit", trace_id, rid,
                                        reason=decision.reason,
                                        deferred=True)
                    drop_request(self.paths["inbox"], req.to_json(), rid)
                else:  # became unservable — resolve the waiting future
                    fut = self._futures.pop(rid, None)
                    self._admitted.pop(rid, None)
                    self._trace_ids.pop(rid, None)
                    self.trace_log.emit("reject", trace_id, rid,
                                        reason=decision.reason)
                    self.trace_log.emit("settle", trace_id, rid,
                                        outcome="rejected")
                    if fut is not None and not fut.done():
                        res = EvalResult.failure(
                            rid,
                            f"rejected: {decision.reason} ({decision.detail})",
                        )
                        res.trace_id = trace_id
                        res.admission = decision.to_json()
                        fut.set_result(res.to_json())
            self._maybe_close_intake()

    # ---- connection handling -----------------------------------------
    async def _handle_connection(self, reader, writer) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._connections.add(task)
            task.add_done_callback(self._connections.discard)
        try:
            first = await reader.readline()
            if not first:
                return
            head = first.decode("utf-8", "replace")
            if head.split(" ", 1)[0] in ("GET", "POST", "PUT"):
                await self._handle_http(head, reader, writer)
            else:
                await self._handle_jsonl(head, reader, writer)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle_jsonl(self, first_line: str, reader, writer) -> None:
        """Raw JSONL: results stream back in completion order."""
        lock = asyncio.Lock()  # serialize concurrent result writes
        pending: list[asyncio.Task] = []

        async def forward(fut: asyncio.Future) -> None:
            payload = await fut
            async with lock:
                writer.write((json.dumps(payload) + "\n").encode())
                await writer.drain()

        async def take(raw: str) -> None:
            raw = raw.strip()
            if not raw:
                return
            try:
                payload = json.loads(raw)
                if not isinstance(payload, dict):
                    raise ValueError(
                        f"request must be a JSON object, got {payload!r:.80}"
                    )
            except (json.JSONDecodeError, ValueError) as e:
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                fut.set_result(
                    EvalResult.failure("<undecoded>", str(e)).to_json()
                )
            else:
                _, fut = self._intake(payload)
            pending.append(asyncio.ensure_future(forward(fut)))

        await take(first_line)
        while True:
            line = await reader.readline()
            if not line:
                break
            await take(line.decode("utf-8", "replace"))
        if pending:
            await asyncio.gather(*pending)

    async def _handle_http(self, request_line: str, reader, writer) -> None:
        """Minimal HTTP: ``GET /metrics`` -> Prometheus text,
        ``GET`` anything else -> status JSON; ``POST`` (JSONL body)
        -> 200 with one result line per request."""
        parts = request_line.split(" ")
        method = parts[0]
        path = parts[1] if len(parts) > 1 else "/"
        content_type = b"application/json"
        length = 0
        while True:
            line = await reader.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("utf-8", "replace").partition(":")
            if name.strip().lower() == "content-length":
                try:
                    length = int(value.strip())
                except ValueError:
                    pass
        if method == "GET" and path.split("?", 1)[0] == "/metrics":
            content_type = b"text/plain; version=0.0.4; charset=utf-8"
            body = self.metrics.render().encode()
        elif method == "GET":
            body = json.dumps(self.status(), default=str).encode()
        else:
            raw = await reader.readexactly(length) if length else b""
            futs = []
            for line_text in raw.decode("utf-8", "replace").splitlines():
                if not line_text.strip():
                    continue
                try:
                    payload = json.loads(line_text)
                    if not isinstance(payload, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    fut: asyncio.Future = (
                        asyncio.get_running_loop().create_future()
                    )
                    fut.set_result(
                        EvalResult.failure("<undecoded>", str(e)).to_json()
                    )
                    futs.append(fut)
                else:
                    futs.append(self._intake(payload)[1])
            results = await asyncio.gather(*futs) if futs else []
            body = "".join(json.dumps(r) + "\n" for r in results).encode()
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: " + content_type + b"\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"Connection: close\r\n\r\n" + body
        )
        await writer.drain()

    # ---- reporting ---------------------------------------------------
    def status(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "requests_seen": self.requests_seen,
            "results_forwarded": self.results_forwarded,
            "pending": len(self._futures),
            "deferred": len(self._deferred),
            "admission": (
                self.admission.summary() if self.admission is not None else None
            ),
        }
        # Per-replica heartbeat staleness in seconds (monotonic now
        # minus last stamp) — reported whether or not a supervisor is
        # attached; with one, the health class rides along.
        ages = heartbeat_ages(self.queue_dir)
        if self.health_provider is not None:
            try:
                replicas = self.health_provider()
                for rid, verdict in replicas.items():
                    if isinstance(verdict, dict):
                        verdict["staleness_s"] = (
                            verdict.get("beat_age_s")
                            if verdict.get("beat_age_s") is not None
                            else ages.get(rid)
                        )
                out["replicas"] = replicas
            except Exception as e:  # status must never take the socket down
                out["replicas"] = {"error": str(e)}
        elif ages:
            out["replicas"] = {
                rid: {"staleness_s": age} for rid, age in sorted(ages.items())
            }
        return out

    # ---- metrics collection ------------------------------------------
    def _collect_queue_metrics(self, reg: MetricsRegistry) -> None:
        """Scrape-time gauges from the queue dir: depth per box,
        dead letters, reclaims, heartbeat staleness, health classes,
        crash-ledger totals.  Read-only — workers publish through the
        files they already write, never a new socket."""
        for box in ("inbox", "claimed", "outbox", "dead", "consumed",
                    "done"):
            try:
                depth = len(os.listdir(self.paths[box]))
            except OSError:
                depth = 0
            reg.set_gauge("qba_queue_files", depth, labels={"box": box})
            if box == "dead":
                reg.set_gauge("qba_queue_dead_letters", depth)
        for rid, age in heartbeat_ages(self.queue_dir).items():
            reg.set_gauge("qba_replica_heartbeat_staleness_seconds",
                          age, labels={"replica": rid})
        reclaims = 0
        try:
            names = os.listdir(self.queue_dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith("summary-") and name.endswith(".json"):
                try:
                    with open(os.path.join(self.queue_dir, name)) as f:
                        reclaims += int(json.load(f).get("reclaimed", 0))
                except (OSError, ValueError, TypeError):
                    pass
        try:
            with open(self.paths["crash_ledger"]) as f:
                ledger = json.load(f)
        except (OSError, ValueError):
            ledger = None
        if isinstance(ledger, dict):
            blame = ledger.get("blame", {})
            if isinstance(blame, dict):
                reclaims += sum(
                    int(e.get("releases", 0)) for e in blame.values()
                    if isinstance(e, dict)
                )
                reg.set_gauge(
                    "qba_supervisor_quarantined",
                    sum(1 for e in blame.values()
                        if isinstance(e, dict) and e.get("quarantined")),
                )
            deaths = ledger.get("deaths")
            if isinstance(deaths, list):
                reg.set_gauge("qba_supervisor_deaths", len(deaths))
        reg.set_gauge("qba_queue_reclaims", reclaims)
        if self.health_provider is not None:
            try:
                states: dict[str, int] = {}
                for verdict in self.health_provider().values():
                    if isinstance(verdict, dict):
                        state = str(verdict.get("state", "unknown"))
                        states[state] = states.get(state, 0) + 1
                for state, count in states.items():
                    reg.set_gauge("qba_fleet_replicas", count,
                                  labels={"state": state})
            except Exception:
                pass
