"""Fleet self-healing: watchdog, crash-loop breaker, poison quarantine.

The pool (PR "fleet") tolerates exactly one failure shape: a worker
killed cleanly whose stale claim a *survivor* reclaims.  This module
adds the supervision loop that handles the rest (the ByMC lesson from
PAPERS.md applied to our own infrastructure — the serving fleet must
keep making progress while some of its participants misbehave):

* **watchdog** — workers heartbeat their lifecycle phase
  (:class:`qba_tpu.serve.queuefs.HeartbeatWriter`); the supervisor ages
  each replica's last beat against a *phase-aware* timeout (a cold XLA
  compile gets :data:`WATCHDOG_PHASE_SCALE` x the base budget, so a
  long compile is "busy", not "hung") and SIGKILLs replicas whose
  beat has gone stale — the only way to catch a SIGSTOP'd or wedged
  worker, which never exits and never beats.
* **blame attribution** — every observed death is cross-referenced
  against the dead worker's last heartbeat: the in-flight request ids
  at death go into a crash ledger keyed by request fingerprint
  (Dapper-style: every failure is *caused*, pinned to a request and a
  replica, never just retried).  The dead worker's claim is released
  back to the inbox immediately — no waiting out the reclaim timeout.
* **poison quarantine** — a request blamed for ``poison_threshold``
  deaths is dead-lettered *now* with a structured crash report
  (``{blamed_replicas, phases, exit_codes, reclaim_count}``), short-
  circuiting the transport's reclaim ladder: one poison request costs
  at most ``poison_threshold`` workers, not ``max_reclaims + 1``.
* **crash-loop breaker** — ``breaker_k`` deaths of one slot inside
  ``breaker_window_s`` benches it: the pool stops respawning it and
  the admission controller releases its share of the capacity window
  (:meth:`~qba_tpu.serve.fleet.admission.AdmissionController.
  bench_replica`), so the fleet degrades gracefully instead of
  queueing against phantom capacity.

Jax-free by construction like the rest of the fleet front half —
:func:`qba_tpu.analysis.transfers.check_fleet` proves it statically,
and also proves the supervisor only ever *reads* heartbeats (writes
stay on the worker side of the KI-6 fence).  docs/KNOWN_ISSUES.md KI-9
names this module + the CI chaos job as the fence against crash-loop /
poison cascades.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

from qba_tpu.obs.tracing import TraceEventLog
from qba_tpu.serve.queuefs import (
    queue_paths,
    read_flight_recorder,
    read_heartbeat,
    request_slug,
    result_path,
    write_json_atomic,
)
from qba_tpu.serve.request import EvalResult
from qba_tpu.serve.timing import (
    BOOT_GRACE_SCALE,
    BREAKER_K,
    BREAKER_WINDOW_S,
    POISON_THRESHOLD,
    SUPERVISOR_POLL_S,
    WATCHDOG_PHASE_SCALE,
    WATCHDOG_S,
)

CRASH_LEDGER_SCHEMA = "qba-tpu/crash-ledger/v1"

# WATCHDOG_PHASE_SCALE is re-exported from qba_tpu.serve.timing (the
# single source for every protocol timing constant) — existing callers
# keep importing it from here.
__all__ = ["FleetSupervisor", "WATCHDOG_PHASE_SCALE", "CRASH_LEDGER_SCHEMA"]

#: Phases during which a death is attributable to the in-flight
#: request(s) the heartbeat names.  An ``idle`` death blames nobody.
_BLAMABLE_PHASES = ("claim", "compile", "dispatch", "readback")


class FleetSupervisor:
    """Poll-driven supervision of one :class:`~qba_tpu.serve.fleet.
    pool.ReplicaPool` (duck-typed: tests drive it with stub pools).

    One :meth:`poll` is one supervision step — classify, kill hung,
    attribute deaths, quarantine or release claims, trip the breaker,
    respawn, persist the crash ledger.  :meth:`run` loops it for the
    CLI's supervisor thread.  The clock is injectable so tests can age
    heartbeats without sleeping.
    """

    def __init__(
        self,
        pool,
        *,
        admission=None,
        watchdog_s: float = WATCHDOG_S,
        breaker_k: int = BREAKER_K,
        breaker_window_s: float = BREAKER_WINDOW_S,
        poison_threshold: int = POISON_THRESHOLD,
        boot_grace_s: float | None = None,
        clock=time.monotonic,
    ) -> None:
        if watchdog_s <= 0:
            raise ValueError(f"watchdog_s must be > 0, got {watchdog_s}")
        if breaker_k < 1:
            raise ValueError(f"breaker_k must be >= 1, got {breaker_k}")
        if poison_threshold < 1:
            raise ValueError(
                f"poison_threshold must be >= 1, got {poison_threshold}"
            )
        self.pool = pool
        self.queue_dir = pool.queue_dir
        self.paths = queue_paths(self.queue_dir)
        self.admission = admission
        self.watchdog_s = watchdog_s
        self.breaker_k = breaker_k
        self.breaker_window_s = breaker_window_s
        self.poison_threshold = poison_threshold
        # Workers importing jax take seconds to boot before their first
        # beat — a fresh pid with no heartbeat yet is booting, not hung.
        self.boot_grace_s = (
            boot_grace_s
            if boot_grace_s is not None
            else BOOT_GRACE_SCALE * watchdog_s
        )
        self._clock = clock
        # Lifecycle trace events (docs/OBSERVABILITY.md): kill /
        # death / release / quarantine stamps carrying the blamed
        # request's trace id, so a stitched trace shows the
        # supervisor's interventions on the request's own timeline.
        self.trace_log = TraceEventLog(self.queue_dir)
        #: Tail length of the dead worker's flight recorder embedded in
        #: death events and KI-9 crash reports.
        self.flight_tail = 16
        self._first_seen: dict[tuple[str, int], float] = {}
        self._handled_deaths: set[tuple[str, int]] = set()
        self._death_events: list[dict[str, Any]] = []
        # Crash ledger: request fingerprint (claim-file slug) ->
        # accumulated blame evidence across worker deaths.
        self.ledger: dict[str, dict[str, Any]] = {}
        self.quarantined: dict[str, dict[str, Any]] = {}
        self.bench_events: list[dict[str, Any]] = []
        self.hung_killed: list[dict[str, Any]] = []
        self.polls = 0

    # ---- classification ----------------------------------------------
    def classify(self, replica) -> dict[str, Any]:
        """One replica's health verdict: ``state`` is one of
        ``healthy|busy|hung|dead`` plus the evidence (phase, beat age,
        pid) the verdict rests on."""
        rid = replica.replica_id
        pid = replica.proc.pid
        now = self._clock()
        out: dict[str, Any] = {"replica_id": rid, "pid": pid}
        if not replica.alive:
            out["state"] = "dead"
            out["exit_code"] = replica.proc.returncode
            return out
        hb = read_heartbeat(self.queue_dir, rid)
        if hb is None or hb.get("pid") != pid:
            # No beat from THIS incarnation yet (a respawn inherits the
            # dead pid's stale file): booting, with a grace period.
            first = self._first_seen.setdefault((rid, pid), now)
            age = now - first
            out["phase"] = "boot"
            out["beat_age_s"] = age
            out["state"] = "hung" if age > self.boot_grace_s else "healthy"
            return out
        phase = str(hb.get("phase", "idle"))
        age = now - float(hb.get("monotonic", now))
        allowed = self.watchdog_s * WATCHDOG_PHASE_SCALE.get(phase, 1.0)
        out["phase"] = phase
        out["beat_age_s"] = age
        out["request_ids"] = list(hb.get("request_ids") or [])
        if age > allowed:
            out["state"] = "hung"
        elif phase == "idle":
            out["state"] = "healthy"
        else:
            out["state"] = "busy"
        return out

    def health(self) -> dict[str, dict[str, Any]]:
        """Per-replica health map for ``GET /status`` — classification
        plus bench state, no side effects."""
        out: dict[str, dict[str, Any]] = {}
        benched = getattr(self.pool, "benched", set())
        for r in self.pool.replicas:
            verdict = self.classify(r)
            verdict["benched"] = r.replica_id in benched
            out[r.replica_id] = verdict
        return out

    # ---- one supervision step ----------------------------------------
    def poll(self) -> dict[str, Any]:
        """One step: kill hung workers, attribute + recover every new
        death, trip the breaker, respawn, persist the crash ledger."""
        self.polls += 1
        verdicts = {r.replica_id: self.classify(r) for r in self.pool.replicas}
        killed = []
        for rid, v in verdicts.items():
            if v["state"] != "hung":
                continue
            try:
                self.pool.kill(rid)
            except ValueError:
                continue  # exited on its own between classify and kill
            event = {
                "replica_id": rid,
                "pid": v["pid"],
                "phase": v.get("phase"),
                "beat_age_s": v.get("beat_age_s"),
                "at": time.time(),
            }
            self.hung_killed.append(event)
            killed.append(rid)
            for req_id in v.get("request_ids") or [None]:
                trace_id, request_id = (
                    self._trace_of(request_slug(req_id))
                    if req_id is not None else (None, None)
                )
                self.trace_log.emit(
                    "kill", trace_id, request_id or req_id,
                    replica_id=rid, pid=v["pid"], phase=v.get("phase"),
                    beat_age_s=v.get("beat_age_s"),
                )
        deaths = self._handle_deaths()
        benched = self._trip_breaker()
        respawned = self.pool.respawn_dead()
        self._write_ledger()
        return {
            "verdicts": verdicts,
            "hung_killed": killed,
            "deaths": deaths,
            "benched": benched,
            "respawned": respawned,
        }

    def run(
        self,
        stop_event: threading.Event,
        poll_s: float = SUPERVISOR_POLL_S,
    ) -> None:
        """Poll until ``stop_event`` is set (the CLI's supervisor
        thread body)."""
        while not stop_event.is_set():
            self.poll()
            stop_event.wait(poll_s)

    # ---- death attribution + recovery --------------------------------
    def _handle_deaths(self) -> list[dict[str, Any]]:
        new: list[dict[str, Any]] = []
        for r in self.pool.replicas:
            if r.alive:
                continue
            key = (r.replica_id, r.proc.pid)
            if key in self._handled_deaths:
                continue
            self._handled_deaths.add(key)
            exit_code = (
                r.proc.returncode
                if r.proc.returncode is not None
                else getattr(r, "returncode", None)
            )
            hb = read_heartbeat(self.queue_dir, r.replica_id)
            phase, rids = "unknown", []
            if hb is not None and hb.get("pid") == r.proc.pid:
                phase = str(hb.get("phase", "unknown"))
                rids = list(hb.get("request_ids") or [])
            event = {
                "replica_id": r.replica_id,
                "pid": r.proc.pid,
                "exit_code": exit_code,
                "phase": phase,
                "request_ids": rids,
                "at": self._clock(),
                "wall": time.time(),
            }
            # Capture the flight-recorder tail NOW: a respawn of this
            # slot will overwrite flight-<slug>.json, but the death
            # event (and any crash report built from it) must keep the
            # dead incarnation's last moments.
            event["flight_recorder"] = read_flight_recorder(
                self.queue_dir, r.replica_id, tail=self.flight_tail
            )
            self._death_events.append(event)
            new.append(event)
            for rid in rids or [None]:
                trace_id, request_id = (
                    self._trace_of(request_slug(rid))
                    if rid is not None else (None, None)
                )
                self.trace_log.emit(
                    "death", trace_id, request_id or rid,
                    replica_id=r.replica_id, pid=r.proc.pid,
                    exit_code=exit_code, phase=phase,
                )
            if phase in _BLAMABLE_PHASES:
                for rid in rids:
                    self._blame(request_slug(rid), event)
        return new

    def _blame(self, slug: str, death: dict[str, Any]) -> None:
        """Charge one request fingerprint with one worker death, then
        recover its claim: quarantine at the poison threshold, release
        back to the inbox below it."""
        entry = self.ledger.setdefault(
            slug, {"deaths": [], "releases": 0, "quarantined": False}
        )
        entry["deaths"].append(
            {
                "replica_id": death["replica_id"],
                "pid": death["pid"],
                "phase": death["phase"],
                "exit_code": death["exit_code"],
                "flight_recorder": death.get("flight_recorder"),
            }
        )
        if entry["quarantined"]:
            return
        if len(entry["deaths"]) >= self.poison_threshold:
            self._quarantine(slug, entry)
        elif self._release_claim(slug):
            entry["releases"] += 1

    def _trace_of(self, slug: str) -> tuple[str | None, str | None]:
        """(trace_id, request_id) from wherever the request's queue
        file currently sits — the trace context rides the file JSON, so
        supervisor events can stamp the same id the worker adopted."""
        for key in ("claimed", "inbox", "dead"):
            path = os.path.join(self.paths[key], f"{slug}.json")
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(payload, dict):
                return (payload.get("trace_id"),
                        payload.get("request_id", slug))
        return None, slug

    def _claim_file(self, slug: str) -> tuple[str, str] | None:
        """Where the blamed request's file currently sits: the dead
        worker's claim, or the inbox (a peer's reclaim ladder may have
        already pushed it back)."""
        for key in ("claimed", "inbox"):
            path = os.path.join(self.paths[key], f"{slug}.json")
            if os.path.exists(path):
                return key, path
        return None

    def _release_claim(self, slug: str) -> bool:
        """Push a dead worker's claim straight back to the inbox — the
        fast path the watchdog enables: re-served within one poll, not
        one reclaim timeout."""
        loc = self._claim_file(slug)
        if loc is None or loc[0] != "claimed":
            return False
        trace_id, request_id = self._trace_of(slug)
        try:
            # qba-protocol: release
            os.replace(
                loc[1], os.path.join(self.paths["inbox"], f"{slug}.json")
            )
        except OSError:
            return False
        self.trace_log.emit("release", trace_id, request_id, slug=slug)
        return True

    def _quarantine(self, slug: str, entry: dict[str, Any]) -> None:
        """Dead-letter a poison request NOW with its crash report —
        wherever its file sits, it must never reach another worker."""
        request_id = slug
        trace_id = None
        loc = self._claim_file(slug)
        if loc is not None:
            try:
                with open(loc[1]) as f:
                    payload = json.loads(f.read())
                request_id = str(payload.get("request_id", slug))
                trace_id = payload.get("trace_id")
            except (OSError, json.JSONDecodeError, AttributeError):
                pass
            try:
                os.makedirs(self.paths["dead"], exist_ok=True)
                # qba-protocol: quarantine
                os.replace(
                    loc[1], os.path.join(self.paths["dead"], f"{slug}.json")
                )
            except OSError:
                pass  # raced away; the crash-report result still wins
        deaths = entry["deaths"]
        # The last blamed worker's flight-recorder tail (captured at
        # death time, before any respawn overwrote the file): the
        # crash report shows what the worker was doing when it died.
        flight = next(
            (d["flight_recorder"] for d in reversed(deaths)
             if d.get("flight_recorder")),
            None,
        ) or {"replica_id": deaths[-1]["replica_id"] if deaths else None,
              "events": []}
        report = {
            "blamed_replicas": [d["replica_id"] for d in deaths],
            "phases": [d["phase"] for d in deaths],
            "exit_codes": [d["exit_code"] for d in deaths],
            "reclaim_count": entry["releases"],
            "flight_recorder": flight,
        }
        entry["quarantined"] = True
        self.quarantined[slug] = {"request_id": request_id, **report}
        self.trace_log.emit("quarantine", trace_id, request_id,
                            slug=slug, deaths=len(deaths))
        self.trace_log.emit("settle", trace_id, request_id,
                            outcome="quarantined")
        res = EvalResult.failure(
            request_id,
            f"quarantined as poison: blamed for {len(deaths)} worker "
            f"death(s) (replicas {report['blamed_replicas']}, phases "
            f"{report['phases']}) — dead-lettered without further retries",
        )
        res.trace_id = trace_id
        res.crash_report = report
        try:
            write_json_atomic(
                result_path(self.paths["outbox"], request_id), res.to_json()
            )
        except OSError:
            pass  # outbox gone (teardown); the ledger still records it

    # ---- breaker ------------------------------------------------------
    def _trip_breaker(self) -> list[str]:
        now = self._clock()
        benched: list[str] = []
        already = getattr(self.pool, "benched", set())
        for r in self.pool.replicas:
            rid = r.replica_id
            if rid in already or rid in benched:
                continue
            recent = [
                e
                for e in self._death_events
                if e["replica_id"] == rid
                and now - e["at"] <= self.breaker_window_s
            ]
            if len(recent) < self.breaker_k:
                continue
            self.pool.bench(rid)
            released = (
                self.admission.bench_replica(rid)
                if self.admission is not None
                else 0
            )
            self.bench_events.append(
                {
                    "replica_id": rid,
                    "deaths_in_window": len(recent),
                    "window_s": self.breaker_window_s,
                    "capacity_released": released,
                    "at": time.time(),
                }
            )
            benched.append(rid)
        return benched

    # ---- persistence / reporting -------------------------------------
    def _write_ledger(self) -> None:
        try:
            write_json_atomic(self.paths["crash_ledger"], self.ledger_json())
        except OSError:
            pass

    def ledger_json(self) -> dict[str, Any]:
        return {
            "schema": CRASH_LEDGER_SCHEMA,
            "blame": self.ledger,
            "quarantined": self.quarantined,
            "bench_events": self.bench_events,
            "hung_killed": self.hung_killed,
            "deaths": self._death_events,
        }

    def summary(self) -> dict[str, Any]:
        """The ``self_healing`` block of ``fleet_summary.json``."""
        return {
            "watchdog_s": self.watchdog_s,
            "polls": self.polls,
            "deaths": len(self._death_events),
            "hung_killed": len(self.hung_killed),
            "respawned": len(getattr(self.pool, "restarted", [])),
            "benched": sorted(getattr(self.pool, "benched", set())),
            "quarantined": dict(self.quarantined),
            "releases": sum(e["releases"] for e in self.ledger.values()),
        }
