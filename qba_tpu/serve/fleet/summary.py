"""Fleet observability: cross-replica aggregation into one summary.

Per-request attribution already exists — every result carries
``replica_id``, ``queue_wait_s``, and ``latency_s`` (its ``request``
span duration), and every replica writes a ``summary-<id>.json`` at
exit.  This module merges those per-process artifacts (Dapper's
cross-process span story, done with files instead of RPC baggage) into
``fleet_summary.json``:

* per-replica request counts and p50/p99 latency (from the replicas'
  own span-derived summaries),
* fleet-wide p50/p99 latency and queue-wait distributions recomputed
  from the outbox results — the exact numbers a client experienced,
* admission decision counts (from the front-end's controller), and
* totals: completed/errored/reclaimed/expired and aggregate req/min.

Jax-free like the rest of the fleet front half; span *files* merge via
:func:`qba_tpu.obs.telemetry.spans_from_jsonl` when a telemetry dir is
given, so Perfetto can show the whole fleet on one timeline.
"""

from __future__ import annotations

import json
import os
from typing import Any

from qba_tpu.obs.telemetry import span_latency_summary, spans_from_jsonl
from qba_tpu.obs.tracing import stitch_traces, trace_summary
from qba_tpu.serve.queuefs import queue_paths, write_json_atomic

FLEET_SUMMARY_SCHEMA = "qba-tpu/fleet-summary/v1"


def _load_results(outbox: str, consumed: str | None = None) -> list[dict[str, Any]]:
    """All result payloads for one fleet run.  The front-end moves a
    result from ``outbox/`` to ``consumed/`` once it is forwarded to
    its caller, so both directories together are the run's results;
    on a filename collision (a request id reused over a live queue
    dir) the outbox copy — the newer, not-yet-forwarded one — wins."""
    by_name: dict[str, dict[str, Any]] = {}
    for directory in (consumed, outbox):
        if directory is None:
            continue
        try:
            names = sorted(os.listdir(directory))
        except OSError:
            continue
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(directory, name)) as f:
                    by_name[name] = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
    return [by_name[name] for name in sorted(by_name)]


def _replica_summaries(queue_dir: str) -> dict[str, dict[str, Any]]:
    out: dict[str, dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(queue_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("summary-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(queue_dir, name)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rid = payload.get("replica_id") or name[len("summary-"):-len(".json")]
        out[str(rid)] = payload
    return out


class _DurSpan:
    """Minimal span stand-in feeding span_latency_summary from result
    latencies (the result's latency_s IS its request-span duration)."""

    __slots__ = ("name", "dur")

    def __init__(self, name: str, dur: float):
        self.name = name
        self.dur = dur


def _distribution(name: str, durs: list[float]) -> dict[str, Any]:
    return span_latency_summary([_DurSpan(name, d) for d in durs], name)


def merge_fleet_spans(telemetry_dir: str) -> list:
    """Every span from every per-request ``spans.jsonl`` under the
    fleet telemetry dir, on one list — the cross-process merge (each
    request span already carries its ``replica_id`` arg)."""
    spans = []
    try:
        entries = sorted(os.listdir(telemetry_dir))
    except OSError:
        return spans
    for entry in entries:
        path = os.path.join(telemetry_dir, entry, "spans.jsonl")
        if os.path.isfile(path):
            spans.extend(spans_from_jsonl(path))
    return spans


def fleet_summary(
    queue_dir: str,
    *,
    admission_summary: dict[str, Any] | None = None,
    frontend_status: dict[str, Any] | None = None,
    elapsed_s: float | None = None,
    telemetry_dir: str | None = None,
    self_healing: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Aggregate one fleet run's artifacts into a summary dict.

    ``self_healing`` is the supervisor's totals
    (:meth:`~qba_tpu.serve.fleet.supervisor.FleetSupervisor.summary`);
    independent of it, quarantined poison requests are totalled from
    their crash-report results and the on-disk crash ledger, so the
    summary stays truthful even for a run whose supervisor died."""
    paths = queue_paths(queue_dir)
    results = _load_results(paths["outbox"], paths["consumed"])
    ok = [r for r in results if not r.get("error")]
    per_replica: dict[str, dict[str, Any]] = {}
    for r in ok:
        rid = str(r.get("replica_id"))
        slot = per_replica.setdefault(
            rid, {"completed": 0, "latencies": [], "queue_waits": []}
        )
        slot["completed"] += 1
        if r.get("latency_s") is not None:
            slot["latencies"].append(float(r["latency_s"]))
        if r.get("queue_wait_s") is not None:
            slot["queue_waits"].append(float(r["queue_wait_s"]))
    replicas: dict[str, dict[str, Any]] = {}
    for rid, slot in sorted(per_replica.items()):
        replicas[rid] = {
            "completed": slot["completed"],
            "latency": _distribution("request", slot["latencies"]),
            "queue_wait": _distribution("queue_wait", slot["queue_waits"]),
        }
    exit_summaries = _replica_summaries(queue_dir)
    for rid, payload in exit_summaries.items():
        replicas.setdefault(rid, {})["exit_summary"] = {
            k: payload.get(k)
            for k in ("completed", "expired", "reclaimed", "restored_plans",
                      "latency", "queue_wait")
        }
    summary: dict[str, Any] = {
        "schema": FLEET_SUMMARY_SCHEMA,
        "results": len(results),
        "completed": len(ok),
        "errored": len(results) - len(ok),
        "replicas": replicas,
        "latency": _distribution(
            "request",
            [float(r["latency_s"]) for r in ok if r.get("latency_s") is not None],
        ),
        "queue_wait": _distribution(
            "queue_wait",
            [
                float(r["queue_wait_s"])
                for r in ok
                if r.get("queue_wait_s") is not None
            ],
        ),
        "reclaimed": sum(
            int(p.get("reclaimed") or 0) for p in exit_summaries.values()
        ),
        "expired": sum(
            int(p.get("expired") or 0) for p in exit_summaries.values()
        ),
    }
    # Poison-quarantine totals (KI-9): every dead-lettered request's
    # structured crash report, keyed by request id.
    crash_reports = {
        str(r.get("request_id")): r["crash_report"]
        for r in results
        if r.get("crash_report")
    }
    summary["quarantined"] = len(crash_reports)
    if crash_reports:
        summary["crash_reports"] = crash_reports
    try:
        with open(paths["crash_ledger"]) as f:
            ledger = json.load(f)
    except (OSError, json.JSONDecodeError):
        ledger = None
    if ledger is not None:
        summary["crash_ledger"] = {
            "blamed_requests": len(ledger.get("blame") or {}),
            "quarantined": len(ledger.get("quarantined") or {}),
            "deaths": len(ledger.get("deaths") or []),
            "hung_killed": len(ledger.get("hung_killed") or []),
            "benched": [
                e.get("replica_id")
                for e in (ledger.get("bench_events") or [])
            ],
        }
    if elapsed_s is not None and elapsed_s > 0:
        summary["elapsed_s"] = elapsed_s
        summary["requests_per_min"] = len(ok) / elapsed_s * 60.0
    if admission_summary is not None:
        summary["admission"] = admission_summary
    if frontend_status is not None:
        summary["frontend"] = frontend_status
    if self_healing is not None:
        summary["self_healing"] = self_healing
    if telemetry_dir is not None:
        merged = merge_fleet_spans(telemetry_dir)
        summary["spans"] = {
            "count": len(merged),
            "request": span_latency_summary(merged, "request"),
        }
    # Stitched-trace satellite: one causally-ordered trace per request
    # (intake -> settle) with an orphan-span count that a healthy run
    # must hold at zero, plus span-coverage percentiles.
    stitched = stitch_traces(queue_dir, telemetry_dir=telemetry_dir)
    if stitched["traces"] or stitched["orphan_spans"]:
        summary["traces"] = trace_summary(stitched)
    return summary


def write_fleet_summary(queue_dir: str, summary: dict[str, Any]) -> str:
    path = os.path.join(queue_dir, "fleet_summary.json")
    write_json_atomic(path, summary)
    return path
