"""Warm-start persistence: the saved-plan artifact.

A serve cache directory (``--cache-dir``) is two artifacts side by side:

* ``xla/`` — JAX's persistent compilation cache
  (:func:`qba_tpu.compile_cache.enable_compile_cache`), which makes the
  *executables* survive restarts;
* ``plans.json`` — every memoized resolver verdict
  (:func:`qba_tpu.ops.round_kernel_tiled.export_resolver_state`), which
  makes the *dispatch decisions* survive restarts, so the second boot
  performs zero compile probes (pinned by tests/test_serve.py via
  ``PROBE_STATS``).

``plans.json`` additionally records the explicit config kwargs of every
shape the server has dispatched, so ``qba-tpu lint --saved-plans`` can
re-trace those exact engine builds through the KI-1/KI-2/KI-3 gates —
plans loaded from disk get the same machine-checked guarantees as
freshly probed ones (:func:`qba_tpu.analysis.driver.saved_plan_configs`).

Concurrency contract (the fleet replica pool shares ONE cache dir):
every read and write happens under an advisory ``flock`` on
``plans.json.lock``, writes go through a writer-unique temp file +
``os.replace``, and a save MERGES with the artifact already on disk
(union of resolver entries and config shapes, local entries winning)
instead of clobbering it — so N replicas flushing concurrently can
never tear the file or drop each other's plans, and the union is what
makes the *second* fleet boot zero-probe on every replica.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fcntl
import json
import os
from typing import Any, Iterator

from qba_tpu.compile_cache import plans_lock_path, plans_path
from qba_tpu.config import QBAConfig

PLANS_SCHEMA = "qba-tpu/saved-plans/v1"

# Config fields that do not affect kernel plans; normalized out so the
# saved config list stays one entry per *shape* (matches the resolver
# keys, which hash on shape/engine knobs only).
_NON_PLAN_FIELDS = ("seed", "trials", "collect_counters")


def plan_config_entry(cfg: QBAConfig) -> dict[str, Any]:
    """The explicit (non-derived) kwargs that rebuild ``cfg``'s shape,
    normalized for plan identity."""
    entry = {
        f.name: getattr(cfg, f.name) for f in dataclasses.fields(QBAConfig)
    }
    for name in _NON_PLAN_FIELDS:
        entry.pop(name, None)
    entry["trials"] = 1
    return entry


@contextlib.contextmanager
def plans_lock(cache_dir: str | None) -> Iterator[None]:
    """Exclusive advisory lock over the ``plans.json`` artifact.

    ``flock`` on a sidecar lock file (never on ``plans.json`` itself —
    ``os.replace`` swaps the inode under concurrent writers, which
    would silently unlock them).  Reentrancy is not needed: every
    caller below takes the lock exactly once, at the top."""
    lock_file = plans_lock_path(cache_dir)
    os.makedirs(os.path.dirname(lock_file) or ".", exist_ok=True)
    with open(lock_file, "a") as fh:
        fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(fh.fileno(), fcntl.LOCK_UN)


def _read_payload(path: str) -> dict[str, Any] | None:
    """Best-effort read of an existing artifact (None on any defect)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(payload, dict) or payload.get("schema") != PLANS_SCHEMA:
        return None
    return payload


def _merge_states(
    old: dict[str, Any] | None, new: dict[str, Any]
) -> dict[str, Any]:
    """Union two resolver-state snapshots, ``new`` entries winning.

    Each section is a ``[[key, value], ...]`` list keyed by nested-list
    resolver keys; the union is keyed on the JSON encoding of the key.
    Snapshots from a different jax version/backend don't merge — their
    verdicts would be rejected at import anyway."""
    if (
        not isinstance(old, dict)
        or old.get("schema") != new.get("schema")
        or old.get("jax_version") != new.get("jax_version")
        or old.get("backend") != new.get("backend")
    ):
        return new

    def union(a: list, b: list) -> list:
        merged: dict[str, Any] = {}
        for k, v in list(a) + list(b):
            merged[json.dumps(k)] = [k, v]
        return [merged[k] for k in sorted(merged)]

    out = dict(new)
    out["resolve"] = union(old.get("resolve", []), new.get("resolve", []))
    out["variant"] = union(old.get("variant", []), new.get("variant", []))
    probe = {}
    for section in ("tiled", "rebuild", "fused", "mega"):
        probe[section] = union(
            old.get("probe", {}).get(section, []),
            new.get("probe", {}).get(section, []),
        )
    out["probe"] = probe
    return out


def save_plans(
    cache_dir: str | None,
    configs: list[QBAConfig] | None = None,
    mesh: dict[str, Any] | None = None,
) -> str:
    """Write ``plans.json`` under ``cache_dir`` from the live resolver
    caches, merged with whatever is already on disk (lock + unique
    temp + atomic rename: concurrent replica flushes interleave to the
    union, never a torn or clobbered file).  Returns the path written.

    ``mesh`` (e.g. ``{"dp": 2, "tp": 4, "tp_comms": "ring"}``) records
    the fleet mesh the plans were captured under, so the next boot's
    admission controller prices against the SHARDED KI-2 ceiling the
    warm-started plans assume rather than the single-chip one.  A save
    without ``mesh`` preserves whatever the artifact already
    records."""
    from qba_tpu.ops.round_kernel_tiled import export_resolver_state

    path = plans_path(cache_dir)
    state = export_resolver_state()
    seen: list[dict[str, Any]] = []
    for cfg in configs or []:
        entry = plan_config_entry(cfg)
        if entry not in seen:
            seen.append(entry)
    with plans_lock(cache_dir):
        prior = _read_payload(path)
        if prior is not None:
            state = _merge_states(prior.get("resolver_state"), state)
            for entry in prior.get("configs", []):
                if entry not in seen:
                    seen.append(entry)
            if mesh is None:
                mesh = prior.get("mesh")
        payload = {
            "schema": PLANS_SCHEMA,
            "resolver_state": state,
            "configs": seen,
            "mesh": mesh,
        }
        # Writer-unique temp name: two processes racing a shared
        # ".tmp" would interleave writes into one file before the
        # renames — pid-suffixing keeps every writer on its own inode.
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return path


def load_plans(cache_dir: str | None) -> int:
    """Restore resolver caches from ``cache_dir``'s ``plans.json``.
    Returns the number of resolver entries restored (0 when the file is
    absent, unreadable, or from an incompatible build — warm start is
    best-effort, a cold boot is always correct).  Reads under the
    artifact lock so a replica booting mid-save of a peer waits for the
    complete file instead of warm-starting from the stale one."""
    from qba_tpu.ops.round_kernel_tiled import import_resolver_state

    path = plans_path(cache_dir)
    with plans_lock(cache_dir):
        payload = _read_payload(path)
    if payload is None:
        return 0
    state = payload.get("resolver_state")
    if not isinstance(state, dict):
        return 0
    return import_resolver_state(state)


def saved_configs(path: str) -> list[QBAConfig]:
    """The dispatched-shape configs recorded in a ``plans.json`` —
    raises ``ValueError`` on a missing/malformed file (lint wants loud
    failures, unlike :func:`load_plans`)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read saved plans {path!r}: {e}") from None
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed saved plans {path!r}: {e}") from None
    if not isinstance(payload, dict) or payload.get("schema") != PLANS_SCHEMA:
        raise ValueError(
            f"{path!r} is not a {PLANS_SCHEMA} artifact "
            f"(schema={payload.get('schema') if isinstance(payload, dict) else None!r})"
        )
    configs = []
    for entry in payload.get("configs", []):
        configs.append(QBAConfig(**entry))
    return configs


def saved_mesh(cache_dir: str | None) -> dict[str, Any] | None:
    """The fleet mesh recorded in ``cache_dir``'s ``plans.json``
    (``{"dp": ..., "tp": ..., "tp_comms": ...}``), or None when the
    artifact is absent, pre-mesh, or unreadable — warm-start metadata
    is best-effort like :func:`load_plans`."""
    with plans_lock(cache_dir):
        payload = _read_payload(plans_path(cache_dir))
    if payload is None:
        return None
    mesh = payload.get("mesh")
    return mesh if isinstance(mesh, dict) else None
