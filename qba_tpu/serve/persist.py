"""Warm-start persistence: the saved-plan artifact.

A serve cache directory (``--cache-dir``) is two artifacts side by side:

* ``xla/`` — JAX's persistent compilation cache
  (:func:`qba_tpu.compile_cache.enable_compile_cache`), which makes the
  *executables* survive restarts;
* ``plans.json`` — every memoized resolver verdict
  (:func:`qba_tpu.ops.round_kernel_tiled.export_resolver_state`), which
  makes the *dispatch decisions* survive restarts, so the second boot
  performs zero compile probes (pinned by tests/test_serve.py via
  ``PROBE_STATS``).

``plans.json`` additionally records the explicit config kwargs of every
shape the server has dispatched, so ``qba-tpu lint --saved-plans`` can
re-trace those exact engine builds through the KI-1/KI-2/KI-3 gates —
plans loaded from disk get the same machine-checked guarantees as
freshly probed ones (:func:`qba_tpu.analysis.driver.saved_plan_configs`).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

from qba_tpu.compile_cache import plans_path
from qba_tpu.config import QBAConfig

PLANS_SCHEMA = "qba-tpu/saved-plans/v1"

# Config fields that do not affect kernel plans; normalized out so the
# saved config list stays one entry per *shape* (matches the resolver
# keys, which hash on shape/engine knobs only).
_NON_PLAN_FIELDS = ("seed", "trials", "collect_counters")


def plan_config_entry(cfg: QBAConfig) -> dict[str, Any]:
    """The explicit (non-derived) kwargs that rebuild ``cfg``'s shape,
    normalized for plan identity."""
    entry = {
        f.name: getattr(cfg, f.name) for f in dataclasses.fields(QBAConfig)
    }
    for name in _NON_PLAN_FIELDS:
        entry.pop(name, None)
    entry["trials"] = 1
    return entry


def save_plans(
    cache_dir: str | None, configs: list[QBAConfig] | None = None
) -> str:
    """Write ``plans.json`` under ``cache_dir`` from the live resolver
    caches.  Returns the path written."""
    from qba_tpu.ops.round_kernel_tiled import export_resolver_state

    path = plans_path(cache_dir)
    seen: list[dict[str, Any]] = []
    for cfg in configs or []:
        entry = plan_config_entry(cfg)
        if entry not in seen:
            seen.append(entry)
    payload = {
        "schema": PLANS_SCHEMA,
        "resolver_state": export_resolver_state(),
        "configs": seen,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_plans(cache_dir: str | None) -> int:
    """Restore resolver caches from ``cache_dir``'s ``plans.json``.
    Returns the number of resolver entries restored (0 when the file is
    absent, unreadable, or from an incompatible build — warm start is
    best-effort, a cold boot is always correct)."""
    from qba_tpu.ops.round_kernel_tiled import import_resolver_state

    path = plans_path(cache_dir)
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return 0
    if not isinstance(payload, dict) or payload.get("schema") != PLANS_SCHEMA:
        return 0
    state = payload.get("resolver_state")
    if not isinstance(state, dict):
        return 0
    return import_resolver_state(state)


def saved_configs(path: str) -> list[QBAConfig]:
    """The dispatched-shape configs recorded in a ``plans.json`` —
    raises ``ValueError`` on a missing/malformed file (lint wants loud
    failures, unlike :func:`load_plans`)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read saved plans {path!r}: {e}") from None
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed saved plans {path!r}: {e}") from None
    if not isinstance(payload, dict) or payload.get("schema") != PLANS_SCHEMA:
        raise ValueError(
            f"{path!r} is not a {PLANS_SCHEMA} artifact "
            f"(schema={payload.get('schema') if isinstance(payload, dict) else None!r})"
        )
    configs = []
    for entry in payload.get("configs", []):
        configs.append(QBAConfig(**entry))
    return configs
