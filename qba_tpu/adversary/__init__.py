"""Vectorized Byzantine fault injection (SURVEY §2.9-2.10).

The reference threads an ``is_biz`` flag through every broadcast
(``tfg.py:101-125,169-181,271-284``); here the adversary is a first-class
configurable model: a per-rank honesty mask, commander equivocation as a
per-recipient order vector, and the 4-action lieutenant attack sampled
independently per (broadcast, recipient) at delivery time
(docs/DIVERGENCES.md D3).
"""

from qba_tpu.adversary.model import (
    assign_dishonest,
    commander_orders,
    corrupt_at_delivery,
    sample_attacks_round,
)

__all__ = [
    "assign_dishonest",
    "commander_orders",
    "corrupt_at_delivery",
    "sample_attacks_round",
]
