"""Vectorized Byzantine fault injection (SURVEY §2.9-2.10).

The reference threads an ``is_biz`` flag through every broadcast
(``tfg.py:101-125,169-181,271-284``); here the adversary is a first-class
configurable model: a per-rank honesty mask, commander equivocation as a
per-recipient order vector, and the 4-action lieutenant attack applied at
delivery time — sampled independently per (broadcast, recipient) under
``attack_scope="delivery"``, or with the reference's shared-object
mutation-leak semantics under ``attack_scope="broadcast"``
(docs/DIVERGENCES.md D3).
"""

from qba_tpu.adversary.model import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    EFFECT_NAMES,
    FORGE_BIT,
    assign_dishonest,
    effect_names,
    commander_orders,
    corrupt_at_delivery,
    raw_attack_draws,
    sample_attacks_round,
)

__all__ = [
    "CLEAR_L_BIT",
    "CLEAR_P_BIT",
    "DROP_BIT",
    "EFFECT_NAMES",
    "FORGE_BIT",
    "effect_names",
    "assign_dishonest",
    "commander_orders",
    "corrupt_at_delivery",
    "raw_attack_draws",
    "sample_attacks_round",
]
