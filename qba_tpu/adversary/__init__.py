"""Vectorized Byzantine fault injection (SURVEY §2.9-2.10).

The reference threads an ``is_biz`` flag through every broadcast
(``tfg.py:101-125,169-181,271-284``); here the adversary is a first-class
configurable model: a per-rank honesty mask, commander equivocation as a
per-recipient order vector, and a strategy-indexed zoo of lieutenant
attacks (``cfg.strategy``: reference / collude / adaptive / split)
applied at delivery time — sampled independently per (broadcast,
recipient) under ``attack_scope="delivery"``, or with the reference's
shared-object mutation-leak semantics under ``attack_scope="broadcast"``
(docs/DIVERGENCES.md D3).  Every strategy compiles down to the same
``(attack, rand_v, late)`` effective-edit arrays, so all engines and
backends consume it unchanged (see :mod:`qba_tpu.adversary.model`).
"""

from qba_tpu.adversary.model import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    EFFECT_NAMES,
    FORGE_BIT,
    FORGE_P_BIT,
    STRATEGIES,
    STRATEGY_FORGE_BOUND,
    AdversaryCtx,
    adversary_ctx,
    assign_dishonest,
    effect_names,
    commander_orders,
    corrupt_at_delivery,
    raw_attack_draws,
    sample_attacks_round,
)

__all__ = [
    "CLEAR_L_BIT",
    "CLEAR_P_BIT",
    "DROP_BIT",
    "EFFECT_NAMES",
    "FORGE_BIT",
    "FORGE_P_BIT",
    "STRATEGIES",
    "STRATEGY_FORGE_BOUND",
    "AdversaryCtx",
    "adversary_ctx",
    "effect_names",
    "assign_dishonest",
    "commander_orders",
    "corrupt_at_delivery",
    "raw_attack_draws",
    "sample_attacks_round",
]
