"""Adversary model: honesty assignment, equivocation, message corruption.

Mirrors the reference's three fault-injection sites:

* ``dishonest_comm`` (``tfg.py:101-125``): rank 0 samples ``nDishonest``
  distinct ranks from ``1..nParties`` — the commander can be dishonest.
* dishonest-commander equivocation (``tfg.py:169-181``): two distinct
  orders ``v1 != v2``, split across lieutenants at rank
  ``(nParties+1)//2``.
* the 4-action dishonest-lieutenant attack (``tfg.py:271-284``): per
  recipient, uniformly pick (0) drop with prob 1/2, (1) replace ``v`` with
  a uniform draw from ``[0, nParties+1)`` (the reference's range — *not*
  ``[0, w)``), (2) clear ``P``, (3) clear ``L``.

Corruption is applied at delivery time with a key derived from
(trial, round, sender, slot, receiver) — distributionally identical to the
reference's send-side sampling, minus its shared-object mutation accident
(docs/DIVERGENCES.md D3).

The strategy zoo (``cfg.strategy``) generalizes the third site into a
family of batched adversary laws.  Every strategy is expressed as the
same effective-edit arrays ``(attack, rand_v, late)`` from
:func:`sample_attacks_round` — the narrow waist all round engines and
backends already consume — so a new strategy automatically runs
bit-identically on xla/pallas/pallas_tiled/pallas_fused/spmd and in the
local/native event trails:

* ``"reference"`` — the law above, byte-identical to historical outputs
  (no new key-tree folds on this path).
* ``"collude"`` — same action law, but every forging traitor writes ONE
  shared per-trial target value (drawn once from the trial's rounds key)
  instead of independent draws: coordinated equivocation.
* ``"adaptive"`` — traitors condition on the packet's round and on the
  value they received from the commander: early rounds
  (``2 * round <= n_rounds``) are drop-heavy reconnaissance (drop 1/2),
  late rounds are forge-heavy (forge 1/2), and the forged order is an
  offset of the sender's own received value (never equal to it, always
  in ``[0, w)`` by modular construction).
* ``"split"`` — distinct commander and lieutenant policies: the
  commander equivocates by rank *parity* (maximally interleaved
  partition, see :func:`commander_orders`) while lieutenants mount
  worst-case P-set forgery — fabricating a *maximal* evidence mask
  (FORGE_P: every particle position claimed present) instead of
  clearing it, half the time also forging ``v``.

Strategies that need per-trial state (the collude target, the adaptive
conditioning on received orders) read it from an :class:`AdversaryCtx`
built once per trial by :func:`adversary_ctx` and threaded into
``sample_attacks_round`` alongside the round index.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.core.types import Packet, empty_evidence


def assign_dishonest(cfg: QBAConfig, key: jax.Array) -> jnp.ndarray:
    """bool[n_parties + 1] honesty mask indexed by rank (rank 0 = QSD,
    always honest).  ``nDishonest`` distinct ranks drawn from
    ``1..n_parties`` without replacement (``tfg.py:105``)."""
    perm = jax.random.permutation(key, jnp.arange(1, cfg.n_parties + 1))
    dishonest_ranks = perm[: cfg.n_dishonest]
    ranks = jnp.arange(cfg.n_parties + 1)
    is_dishonest = jnp.any(ranks[:, None] == dishonest_ranks[None, :], axis=1)
    return ~is_dishonest


def commander_orders(
    cfg: QBAConfig, key: jax.Array, commander_honest: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-lieutenant order vector and the commander's own order.

    Honest: one uniform ``v`` sent to everyone (``tfg.py:329``).
    Dishonest: ``v1 != v2`` uniform, lieutenants at rank
    ``i <= (nParties+1)//2`` get ``v1``, the rest ``v2``
    (``tfg.py:169-181``); the commander still *decides* its privately
    chosen ``v`` (``tfg.py:303-305,358`` — the equivocation values are
    local to the broadcast).

    Returns ``(v_sent: int32[n_lieutenants], v_comm: int32)``.
    """
    k_v, k_1, k_2 = jax.random.split(key, 3)
    w = cfg.w
    v = jax.random.randint(k_v, (), 0, w, dtype=jnp.int32)
    v1 = jax.random.randint(k_1, (), 0, w, dtype=jnp.int32)
    # Uniform over the w-1 values != v1 — same law as the reference's
    # rejection loop (tfg.py:173-175).
    v2 = (v1 + 1 + jax.random.randint(k_2, (), 0, w - 1, dtype=jnp.int32)) % w
    ranks = jnp.arange(2, cfg.n_parties + 1, dtype=jnp.int32)
    if cfg.strategy == "split":
        # Split-strategy commander policy: equivocate by rank PARITY —
        # the maximally interleaved partition, so no contiguous majority
        # bloc shares an order (vs the reference's midpoint split).  The
        # v/v1/v2 draws reuse the reference's key discipline so the
        # commander's decided order distribution is unchanged.
        equivocated = jnp.where(ranks % 2 == 0, v1, v2)
    else:
        equivocated = jnp.where(ranks <= (cfg.n_parties + 1) // 2, v1, v2)
    v_sent = jnp.where(commander_honest, v, equivocated)
    return v_sent, v


# Tags folded into the per-round key — each variable is ONE batched draw
# over every (receiver, cell) of the round.  Per-cell key derivation
# (fold_in per cell, then per draw) costs a full threefry chain per cell
# and dominated the whole round loop on TPU (~450 ms/round at 1000
# trials); batched counter-mode draws are ~free.  The three attack
# variables further share a single uint32 stream (bit-sliced), since
# three separate threefry streams were ~6 ms per 1000-trial batch.
_ATTACK_TAG = 0x0AC7
_LATE_TAG = 0x17A7E
# Fresh tags for the zoo strategies' extra draws.  fold_in with a new
# tag opens an independent counter-mode stream, so the reference
# strategy (which never folds these) keeps its historical bit-identity.
_COLLUDE_TAG = 0xC011
_ADAPT_TAG = 0xADA7

# Effective-edit bitmask: the attacks a receiver actually observes on one
# delivery.  Disjoint edits, so leaked combinations under
# attack_scope="broadcast" compose (e.g. forged v AND cleared P).
DROP_BIT = 1  # action 0 with coin 0 (tfg.py:274)
FORGE_BIT = 2  # action 1: v replaced (tfg.py:277)
CLEAR_P_BIT = 4  # action 2 (tfg.py:281)
CLEAR_L_BIT = 8  # action 3 (tfg.py:283)
FORGE_P_BIT = 16  # strategy="split": fabricate a MAXIMAL presence mask

# The strategy zoo — single source of truth for config validation and
# the dispatch in sample_attacks_round.
STRATEGIES = ("reference", "collude", "adaptive", "split")

# Exclusive upper bound of each strategy's forged-order values, as a
# function of the config.  sample_attacks_round refuses (ValueError, not
# a silent clamp) any strategy whose forged values could leave [0, w) —
# the value domain the engines' verdict identities are exact on.  The
# "adaptive" law is modular in w by construction; the others reuse the
# reference's [0, nParties+1) range.
STRATEGY_FORGE_BOUND = {
    "reference": lambda cfg: cfg.n_parties + 1,
    "collude": lambda cfg: cfg.n_parties + 1,
    "adaptive": lambda cfg: cfg.w,
    "split": lambda cfg: cfg.n_parties + 1,
}

# tfg.py:272-284 — trail names for the attack edits, shared by every
# backend that renders protocol events so the trails cannot drift
# (asserted equal across jax/local/native in tests/test_event_trail.py).
EFFECT_NAMES = (
    (DROP_BIT, "drop"),
    (FORGE_BIT, "corrupt-v"),
    (CLEAR_P_BIT, "clear-P"),
    (CLEAR_L_BIT, "clear-L"),
    (FORGE_P_BIT, "forge-P"),
)


def effect_names(bits: int) -> str:
    """Human-readable rendering of an attack bitmask for the event trail."""
    names = [n for b, n in EFFECT_NAMES if bits & b]
    return "+".join(names) if names else "none"


def raw_attack_draws(cfg: QBAConfig, k_round: jax.Array):
    """The round's raw per-(cell, receiver) draws ``(action, coin,
    rand_v)``, each ``[n_lieutenants * slots, n_lieutenants]`` indexed by
    ``(sender * slots + slot, receiver)``:

    * ``action`` — uniform in ``{0..3}``: the 4-way dishonest choice
      (``tfg.py:272``).
    * ``coin`` — uniform in ``{0,1}``: the drop coin for action 0
      (``tfg.py:274``).
    * ``rand_v`` — uniform in ``[0, nParties+1)``: the forged order for
      action 1 (``tfg.py:277`` — the reference's range, *not* ``[0,w)``).

    The three variables are disjoint bit fields of one uint32 stream:
    bits 0-1 = action, bit 2 = coin, bits 3-26 = the dividend for
    ``rand_v``'s modulo (24-bit remainder bias < 2^-20 — the reference's
    own ``np.random.randint`` carries the same class of modulo bias).
    """
    shape = (cfg.n_lieutenants * cfg.slots, cfg.n_lieutenants)
    # Value-range invariant (ADVICE r4): forged orders must stay inside
    # [0, w) — the engines' verdict identities (rounds/engine.py) and
    # the kernels' flag algebra assume every value they see is in
    # [0, w).  The reference's forge range [0, nParties+1) satisfies it
    # only because w = 2**ceil(log2(nParties+1)) >= nParties+1 by
    # construction; enforce that here so a future action with a wider
    # range fails loudly instead of silently shifting verdicts.
    if cfg.n_parties + 1 > cfg.w:  # survives -O, unlike assert
        raise ValueError(
            f"forge range [0, {cfg.n_parties + 1}) exceeds the value "
            f"domain [0, {cfg.w}) the round engines are exact on"
        )
    bits = jax.random.bits(
        jax.random.fold_in(k_round, _ATTACK_TAG), shape, jnp.uint32
    )
    action = (bits & 3).astype(jnp.int32)
    coin = ((bits >> 2) & 1).astype(jnp.int32)
    rand_v = (
        ((bits >> 3) & 0xFFFFFF).astype(jnp.int32) % (cfg.n_parties + 1)
    )
    return action, coin, rand_v


class AdversaryCtx(NamedTuple):
    """Per-trial adversary state threaded into :func:`sample_attacks_round`.

    Built once per trial (outside the round loop) by
    :func:`adversary_ctx`; ``None`` stands in for strategies that are
    stateless across rounds ("reference", "split").

    Attributes:
      collude_target: int32 scalar — the one shared forged order every
        colluding traitor writes ("collude").
      v_sent: int32[n_lieutenants] — the order each lieutenant received
        from the commander, the conditioning value for "adaptive".
    """

    collude_target: jax.Array
    v_sent: jax.Array


def adversary_ctx(
    cfg: QBAConfig, k_rounds: jax.Array, v_sent: jax.Array
) -> AdversaryCtx | None:
    """Build the per-trial :class:`AdversaryCtx` for ``cfg.strategy``.

    ``k_rounds`` is the trial's rounds key (the same key the round loop
    folds round indices into); the collude target opens an independent
    stream from it via ``_COLLUDE_TAG``, so the per-round attack draws
    are unperturbed.  Returns ``None`` for stateless strategies — the
    reference path stays byte-identical because nothing new is drawn.
    """
    if cfg.strategy in ("reference", "split"):
        return None
    target = jax.random.randint(
        jax.random.fold_in(k_rounds, _COLLUDE_TAG),
        (),
        0,
        cfg.n_parties + 1,
        dtype=jnp.int32,
    )
    return AdversaryCtx(collude_target=target, v_sent=v_sent)


def sample_attacks_round(
    cfg: QBAConfig,
    k_round: jax.Array,
    round_idx: jax.Array | int | None = None,
    ctx: AdversaryCtx | None = None,
):
    """Draw one round's attack randomness under ``cfg.strategy`` and fold
    in the attack scope.

    Returns ``(attack, rand_v, late)``, each
    ``[n_lieutenants * slots, n_lieutenants]`` indexed by
    ``(sender * slots + slot, receiver)`` — packet-major, so the Pallas
    round kernel reads one receiver's draws as a relayout-free lane
    slice and no engine ever materializes a transpose:

    * ``attack`` — int32 bitmask of the edits this receiver observes
      (DROP/FORGE/CLEAR_P/CLEAR_L/FORGE_P bits above).  Under the
      default ``attack_scope="delivery"`` the bits are this delivery's
      strategy action, applied independently per delivery.  Under
      ``attack_scope="broadcast"`` (reference strategy only) the
      forge/clear bits are the *cumulative leaked state* of the
      reference's shared-object mutations (``tfg.py:271-284``):
      ``P.clear()`` / ``L.clear()`` at one recipient persist for every
      later recipient of the same broadcast, and an action-1 ``v``
      reassignment carries forward until the next action-1 draw.  The
      drop bit never leaks (``sent`` resets per recipient,
      ``tfg.py:270``).
    * ``rand_v`` — the forged order accompanying the FORGE bit; under
      broadcast scope, the draw of the *most recent* forging recipient
      in rank order.
    * ``late`` — the racy-delivery loss flag (docs/DIVERGENCES.md D1);
      all-False under ``delivery="sync"`` so sync and racy-with-p_late=0
      runs are bit-identical.

    ``round_idx`` (the 1-based protocol round) and ``ctx`` (from
    :func:`adversary_ctx`) are consumed by the strategies that condition
    on them ("adaptive" needs both, "collude" needs ``ctx``); the
    reference law ignores them, so existing two-argument callers are
    unchanged.  All strategies draw the action stream from the same
    ``_ATTACK_TAG`` fold, so switching strategy never perturbs the rest
    of the key tree.

    The broadcast leak chain runs along the receiver axis in rank order,
    skipping the sender's own column (the reference's recipient loop
    skips self *before* drawing, ``tfg.py:267-269``).  All three
    protocol backends (jax / local / native) consume exactly these
    effective arrays, so their randomness matches bit for bit in any
    scope or strategy.
    """
    shape = (cfg.n_lieutenants * cfg.slots, cfg.n_lieutenants)
    bound = STRATEGY_FORGE_BOUND[cfg.strategy](cfg)
    if bound > cfg.w:  # survives -O, unlike assert
        raise ValueError(
            f"strategy {cfg.strategy!r} forges orders in [0, {bound}), "
            f"outside the value domain [0, {cfg.w}) the round engines "
            "are exact on"
        )
    action, coin, rand_v = raw_attack_draws(cfg, k_round)
    forge_p = None
    if cfg.strategy == "reference":
        drop = (action == 0) & (coin == 0)
        forge = action == 1
        clear_p = action == 2
        clear_l = action == 3
    elif cfg.strategy == "collude":
        # Reference action law; the forged value is the ONE shared
        # per-trial target — coordinated equivocation.
        if ctx is None:
            raise ValueError(
                "strategy='collude' requires ctx=adversary_ctx(...)"
            )
        drop = (action == 0) & (coin == 0)
        forge = action == 1
        clear_p = action == 2
        clear_l = action == 3
        rand_v = jnp.broadcast_to(
            ctx.collude_target.astype(jnp.int32), shape
        )
    elif cfg.strategy == "adaptive":
        # Phase-conditioned law from the 3-bit uniform action*2+coin:
        # early rounds (2*round <= n_rounds) drop half of everything
        # (reconnaissance), late rounds forge half of everything; the
        # remaining 4 outcomes are uniform at 1/8 each.
        if round_idx is None or ctx is None:
            raise ValueError(
                "strategy='adaptive' requires round_idx and "
                "ctx=adversary_ctx(...)"
            )
        u3 = action * 2 + coin  # uniform {0..7}
        late_phase = (
            2 * jnp.asarray(round_idx, dtype=jnp.int32) > cfg.n_rounds
        )
        drop = jnp.where(late_phase, u3 == 4, u3 < 4)
        forge = jnp.where(late_phase, u3 < 4, u3 == 6)
        clear_p = jnp.where(late_phase, u3 == 5, u3 == 4)
        clear_l = jnp.where(late_phase, u3 == 6, u3 == 5)
        # Forged order = sender's received order + nonzero offset mod w:
        # never the value the traitor was told, always in [0, w).
        bits2 = jax.random.bits(
            jax.random.fold_in(k_round, _ADAPT_TAG), shape, jnp.uint32
        )
        offset = (
            ((bits2 & 0xFFFFFF) % max(cfg.w - 1, 1)).astype(jnp.int32) + 1
        )
        senders = jnp.arange(shape[0], dtype=jnp.int32) // cfg.slots
        v_recv = ctx.v_sent.astype(jnp.int32)[senders][:, None]
        rand_v = (v_recv + offset) % cfg.w
    elif cfg.strategy == "split":
        # Lieutenant policy: worst-case P-set forgery.  action 0 ->
        # fabricate a maximal presence mask (FORGE_P); action 1 ->
        # FORGE_P and forge v too; action 2 -> clear L; action 3 ->
        # drop with the coin (1/8 drop, 1/8 clean).  P is never cleared
        # — it is always *inflated*.
        forge_p = (action == 0) | (action == 1)
        forge = action == 1
        clear_l = action == 2
        drop = (action == 3) & (coin == 0)
        clear_p = jnp.zeros(shape, dtype=bool)
    else:  # pragma: no cover — config validation owns membership
        raise ValueError(f"unknown strategy {cfg.strategy!r}")
    if cfg.attack_scope == "broadcast":
        senders = jnp.arange(shape[0], dtype=jnp.int32)[:, None] // cfg.slots
        recv = jnp.arange(cfg.n_lieutenants, dtype=jnp.int32)[None, :]
        not_self = senders != recv
        # Last forging recipient <= this one (rank order): running max of
        # the forging column indices; -1 = none yet.
        last_forge = jax.lax.cummax(
            jnp.where(forge & not_self, recv, -1), axis=1
        )
        forge = last_forge >= 0
        rand_v = jnp.take_along_axis(
            rand_v, jnp.maximum(last_forge, 0), axis=1
        )
        clear_p = (
            jax.lax.cummax((clear_p & not_self).astype(jnp.int32), axis=1) > 0
        )
        clear_l = (
            jax.lax.cummax((clear_l & not_self).astype(jnp.int32), axis=1) > 0
        )
    attack = (
        drop * DROP_BIT
        + forge * FORGE_BIT
        + clear_p * CLEAR_P_BIT
        + clear_l * CLEAR_L_BIT
    ).astype(jnp.int32)
    if forge_p is not None:
        # Added as a separate term so the reference path's arithmetic —
        # and hence its jaxpr and outputs — is untouched.
        attack = attack + (forge_p * FORGE_P_BIT).astype(jnp.int32)
    if cfg.delivery == "racy":
        late = jax.random.bernoulli(
            jax.random.fold_in(k_round, _LATE_TAG), cfg.p_late, shape
        )
    else:
        late = jnp.zeros(shape, dtype=bool)
    return attack, rand_v, late


def corrupt_at_delivery(
    cfg: QBAConfig,
    draws: tuple[jnp.ndarray, jnp.ndarray],
    packet: Packet,
    sender_honest: jnp.ndarray,
) -> tuple[Packet, jnp.ndarray]:
    """Apply the effective attack edits to one delivered packet, consuming
    this cell's ``(attack, rand_v)`` scalars from
    :func:`sample_attacks_round`.

    Returns ``(packet', delivered)``; no-op (and always delivered) when the
    sender is honest.
    """
    attack, rand_v = draws
    biz = ~sender_honest

    # Drop: action 0 with coin 0 (tfg.py:274).
    delivered = ~(biz & ((attack & DROP_BIT) != 0))

    # Forged order from [0, nParties+1) (tfg.py:277).
    v = jnp.where(biz & ((attack & FORGE_BIT) != 0), rand_v, packet.v)

    # Clear P (tfg.py:281).
    p_mask = jnp.where(
        biz & ((attack & CLEAR_P_BIT) != 0), False, packet.p_mask
    )

    # Forge P (strategy="split"): fabricate a MAXIMAL presence mask —
    # every particle position claimed.  Applied after CLEAR_P so forgery
    # wins if both bits ever compose.
    p_mask = jnp.where(
        biz & ((attack & FORGE_P_BIT) != 0), True, p_mask
    )

    # Clear L (tfg.py:283).
    empty = empty_evidence(*packet.evidence.vals.shape)
    clear_l = biz & ((attack & CLEAR_L_BIT) != 0)
    evidence = jax.tree.map(
        lambda e, z: jnp.where(clear_l, z, e), packet.evidence, empty
    )

    return Packet(p_mask=p_mask, v=v, evidence=evidence), delivered
