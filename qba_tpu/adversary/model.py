"""Adversary model: honesty assignment, equivocation, message corruption.

Mirrors the reference's three fault-injection sites:

* ``dishonest_comm`` (``tfg.py:101-125``): rank 0 samples ``nDishonest``
  distinct ranks from ``1..nParties`` — the commander can be dishonest.
* dishonest-commander equivocation (``tfg.py:169-181``): two distinct
  orders ``v1 != v2``, split across lieutenants at rank
  ``(nParties+1)//2``.
* the 4-action dishonest-lieutenant attack (``tfg.py:271-284``): per
  recipient, uniformly pick (0) drop with prob 1/2, (1) replace ``v`` with
  a uniform draw from ``[0, nParties+1)`` (the reference's range — *not*
  ``[0, w)``), (2) clear ``P``, (3) clear ``L``.

Corruption is applied at delivery time with a key derived from
(trial, round, sender, slot, receiver) — distributionally identical to the
reference's send-side sampling, minus its shared-object mutation accident
(docs/DIVERGENCES.md D3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.core.types import Packet, empty_evidence


def assign_dishonest(cfg: QBAConfig, key: jax.Array) -> jnp.ndarray:
    """bool[n_parties + 1] honesty mask indexed by rank (rank 0 = QSD,
    always honest).  ``nDishonest`` distinct ranks drawn from
    ``1..n_parties`` without replacement (``tfg.py:105``)."""
    perm = jax.random.permutation(key, jnp.arange(1, cfg.n_parties + 1))
    dishonest_ranks = perm[: cfg.n_dishonest]
    ranks = jnp.arange(cfg.n_parties + 1)
    is_dishonest = jnp.any(ranks[:, None] == dishonest_ranks[None, :], axis=1)
    return ~is_dishonest


def commander_orders(
    cfg: QBAConfig, key: jax.Array, commander_honest: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-lieutenant order vector and the commander's own order.

    Honest: one uniform ``v`` sent to everyone (``tfg.py:329``).
    Dishonest: ``v1 != v2`` uniform, lieutenants at rank
    ``i <= (nParties+1)//2`` get ``v1``, the rest ``v2``
    (``tfg.py:169-181``); the commander still *decides* its privately
    chosen ``v`` (``tfg.py:303-305,358`` — the equivocation values are
    local to the broadcast).

    Returns ``(v_sent: int32[n_lieutenants], v_comm: int32)``.
    """
    k_v, k_1, k_2 = jax.random.split(key, 3)
    w = cfg.w
    v = jax.random.randint(k_v, (), 0, w, dtype=jnp.int32)
    v1 = jax.random.randint(k_1, (), 0, w, dtype=jnp.int32)
    # Uniform over the w-1 values != v1 — same law as the reference's
    # rejection loop (tfg.py:173-175).
    v2 = (v1 + 1 + jax.random.randint(k_2, (), 0, w - 1, dtype=jnp.int32)) % w
    ranks = jnp.arange(2, cfg.n_parties + 1, dtype=jnp.int32)
    equivocated = jnp.where(ranks <= (cfg.n_parties + 1) // 2, v1, v2)
    v_sent = jnp.where(commander_honest, v, equivocated)
    return v_sent, v


# Tags folded into the per-round key — each variable is ONE batched draw
# over every (receiver, cell) of the round.  Per-cell key derivation
# (fold_in per cell, then per draw) costs a full threefry chain per cell
# and dominated the whole round loop on TPU (~450 ms/round at 1000
# trials); batched counter-mode draws are ~free.  The three attack
# variables further share a single uint32 stream (bit-sliced), since
# three separate threefry streams were ~6 ms per 1000-trial batch.
_ATTACK_TAG = 0x0AC7
_LATE_TAG = 0x17A7E

# Effective-edit bitmask: the attacks a receiver actually observes on one
# delivery.  Disjoint edits, so leaked combinations under
# attack_scope="broadcast" compose (e.g. forged v AND cleared P).
DROP_BIT = 1  # action 0 with coin 0 (tfg.py:274)
FORGE_BIT = 2  # action 1: v replaced (tfg.py:277)
CLEAR_P_BIT = 4  # action 2 (tfg.py:281)
CLEAR_L_BIT = 8  # action 3 (tfg.py:283)

# tfg.py:272-284 — trail names for the attack edits, shared by every
# backend that renders protocol events so the trails cannot drift.
EFFECT_NAMES = (
    (DROP_BIT, "drop"),
    (FORGE_BIT, "corrupt-v"),
    (CLEAR_P_BIT, "clear-P"),
    (CLEAR_L_BIT, "clear-L"),
)


def effect_names(bits: int) -> str:
    """Human-readable rendering of an attack bitmask for the event trail."""
    names = [n for b, n in EFFECT_NAMES if bits & b]
    return "+".join(names) if names else "none"


def raw_attack_draws(cfg: QBAConfig, k_round: jax.Array):
    """The round's raw per-(cell, receiver) draws ``(action, coin,
    rand_v)``, each ``[n_lieutenants * slots, n_lieutenants]`` indexed by
    ``(sender * slots + slot, receiver)``:

    * ``action`` — uniform in ``{0..3}``: the 4-way dishonest choice
      (``tfg.py:272``).
    * ``coin`` — uniform in ``{0,1}``: the drop coin for action 0
      (``tfg.py:274``).
    * ``rand_v`` — uniform in ``[0, nParties+1)``: the forged order for
      action 1 (``tfg.py:277`` — the reference's range, *not* ``[0,w)``).

    The three variables are disjoint bit fields of one uint32 stream:
    bits 0-1 = action, bit 2 = coin, bits 3-26 = the dividend for
    ``rand_v``'s modulo (24-bit remainder bias < 2^-20 — the reference's
    own ``np.random.randint`` carries the same class of modulo bias).
    """
    shape = (cfg.n_lieutenants * cfg.slots, cfg.n_lieutenants)
    # Value-range invariant (ADVICE r4): forged orders must stay inside
    # [0, w) — the engines' verdict identities (rounds/engine.py) and
    # the kernels' flag algebra assume every value they see is in
    # [0, w).  The reference's forge range [0, nParties+1) satisfies it
    # only because w = 2**ceil(log2(nParties+1)) >= nParties+1 by
    # construction; enforce that here so a future action with a wider
    # range fails loudly instead of silently shifting verdicts.
    if cfg.n_parties + 1 > cfg.w:  # survives -O, unlike assert
        raise ValueError(
            f"forge range [0, {cfg.n_parties + 1}) exceeds the value "
            f"domain [0, {cfg.w}) the round engines are exact on"
        )
    bits = jax.random.bits(
        jax.random.fold_in(k_round, _ATTACK_TAG), shape, jnp.uint32
    )
    action = (bits & 3).astype(jnp.int32)
    coin = ((bits >> 2) & 1).astype(jnp.int32)
    rand_v = (
        ((bits >> 3) & 0xFFFFFF).astype(jnp.int32) % (cfg.n_parties + 1)
    )
    return action, coin, rand_v


def sample_attacks_round(cfg: QBAConfig, k_round: jax.Array):
    """Draw one round's attack randomness and fold in the attack scope.

    Returns ``(attack, rand_v, late)``, each
    ``[n_lieutenants * slots, n_lieutenants]`` indexed by
    ``(sender * slots + slot, receiver)`` — packet-major, so the Pallas
    round kernel reads one receiver's draws as a relayout-free lane
    slice and no engine ever materializes a transpose:

    * ``attack`` — int32 bitmask of the edits this receiver observes
      (DROP/FORGE/CLEAR_P/CLEAR_L bits above).  Under the default
      ``attack_scope="delivery"`` at most one bit is set — the raw
      per-recipient action, applied independently per delivery.  Under
      ``attack_scope="broadcast"`` the forge/clear bits are the
      *cumulative leaked state* of the reference's shared-object
      mutations (``tfg.py:271-284``): ``P.clear()`` / ``L.clear()`` at
      one recipient persist for every later recipient of the same
      broadcast, and an action-1 ``v`` reassignment carries forward
      until the next action-1 draw.  The drop bit never leaks (``sent``
      resets per recipient, ``tfg.py:270``).
    * ``rand_v`` — the forged order accompanying the FORGE bit; under
      broadcast scope, the draw of the *most recent* forging recipient
      in rank order.
    * ``late`` — the racy-delivery loss flag (docs/DIVERGENCES.md D1);
      all-False under ``delivery="sync"`` so sync and racy-with-p_late=0
      runs are bit-identical.

    The leak chain runs along the receiver axis in rank order, skipping
    the sender's own column (the reference's recipient loop skips self
    *before* drawing, ``tfg.py:267-269``).  All three protocol backends
    (jax / local / native) consume exactly these effective arrays, so
    their randomness matches bit for bit in either scope.
    """
    shape = (cfg.n_lieutenants * cfg.slots, cfg.n_lieutenants)
    action, coin, rand_v = raw_attack_draws(cfg, k_round)
    drop = (action == 0) & (coin == 0)
    forge = action == 1
    clear_p = action == 2
    clear_l = action == 3
    if cfg.attack_scope == "broadcast":
        senders = jnp.arange(shape[0], dtype=jnp.int32)[:, None] // cfg.slots
        recv = jnp.arange(cfg.n_lieutenants, dtype=jnp.int32)[None, :]
        not_self = senders != recv
        # Last forging recipient <= this one (rank order): running max of
        # the forging column indices; -1 = none yet.
        last_forge = jax.lax.cummax(
            jnp.where(forge & not_self, recv, -1), axis=1
        )
        forge = last_forge >= 0
        rand_v = jnp.take_along_axis(
            rand_v, jnp.maximum(last_forge, 0), axis=1
        )
        clear_p = (
            jax.lax.cummax((clear_p & not_self).astype(jnp.int32), axis=1) > 0
        )
        clear_l = (
            jax.lax.cummax((clear_l & not_self).astype(jnp.int32), axis=1) > 0
        )
    attack = (
        drop * DROP_BIT
        + forge * FORGE_BIT
        + clear_p * CLEAR_P_BIT
        + clear_l * CLEAR_L_BIT
    ).astype(jnp.int32)
    if cfg.delivery == "racy":
        late = jax.random.bernoulli(
            jax.random.fold_in(k_round, _LATE_TAG), cfg.p_late, shape
        )
    else:
        late = jnp.zeros(shape, dtype=bool)
    return attack, rand_v, late


def corrupt_at_delivery(
    cfg: QBAConfig,
    draws: tuple[jnp.ndarray, jnp.ndarray],
    packet: Packet,
    sender_honest: jnp.ndarray,
) -> tuple[Packet, jnp.ndarray]:
    """Apply the effective attack edits to one delivered packet, consuming
    this cell's ``(attack, rand_v)`` scalars from
    :func:`sample_attacks_round`.

    Returns ``(packet', delivered)``; no-op (and always delivered) when the
    sender is honest.
    """
    attack, rand_v = draws
    biz = ~sender_honest

    # Drop: action 0 with coin 0 (tfg.py:274).
    delivered = ~(biz & ((attack & DROP_BIT) != 0))

    # Forged order from [0, nParties+1) (tfg.py:277).
    v = jnp.where(biz & ((attack & FORGE_BIT) != 0), rand_v, packet.v)

    # Clear P (tfg.py:281).
    p_mask = jnp.where(
        biz & ((attack & CLEAR_P_BIT) != 0), False, packet.p_mask
    )

    # Clear L (tfg.py:283).
    empty = empty_evidence(*packet.evidence.vals.shape)
    clear_l = biz & ((attack & CLEAR_L_BIT) != 0)
    evidence = jax.tree.map(
        lambda e, z: jnp.where(clear_l, z, e), packet.evidence, empty
    )

    return Packet(p_mask=p_mask, v=v, evidence=evidence), delivered
