"""One launch = one trial: the round loop, in-kernel.

:func:`build_trial_megakernel` emits a SINGLE ``pallas_call`` whose body

* decodes step 3a on entry (the commander packet consistency verdict +
  compacted-pool build that :func:`qba_tpu.rounds.engine.step3a_one` /
  :func:`qba_tpu.ops.round_kernel_tiled.pool_from_step3a` perform on the
  host for every other engine),
* runs a ``fori_loop`` over all ``n_dishonest + 1`` voting rounds with
  the ``vi`` carry, the ``acc``/slot tables, and BOTH mailbox pools
  (ping-pong A/B) held in VMEM scratch — no HBM round trip between
  rounds, no per-round launch, and
* reduces the per-lieutenant decision (``min(Vi)`` / sentinel ``w``,
  :func:`qba_tpu.core.decide.decide_order`) on exit.

The per-round verdict math is :func:`_verdict_block_accepts` and the
successor-pool build mirrors ``build_fused_round_kernel`` statement for
statement, so the megakernel is bit-identical to the ``pallas_fused``
engine by construction (pinned by tests/test_trial_megakernel.py).  The
entry decode mirrors ``step3a_one``'s ``consistent`` predicate on the
single appended own row (conditions 1/3 are vacuous there) and
``pool_from_step3a``'s prefix-count compaction, as one-hot MXU gathers.

Adversary draws arrive PRE-SAMPLED for all rounds, stacked round-major
(``[n_rounds, (k,) n_cells, n_rv]``): ``jax.random.fold_in`` is value
deterministic, so the host loop that stacks them reproduces exactly the
per-round keys the scanned engines fold in, and the kernel selects a
round's slab by a dynamic index on the leading (majormost) axis.

Trial packing (``trial_pack = k > 1``) folds ``k`` independent trials
into one launch, same layout contract as the packed fused kernel: a
leading ``k`` axis on every trial-varying operand/output/scratch, the
kernel touching only slice ``t`` per trial.

``ProtocolCounters`` are NOT produced here — the loop the counters
wrap no longer exists on the host.  ``rounds/engine.py`` records a
``QBADemotionWarning`` demotion to ``pallas_fused`` when counters are
requested (the ``scan_rounds(collect=True)`` seam).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL
from qba_tpu.ops.round_kernel import (
    CompilerParams,
    _lane_group,
    vma_struct,
)
from qba_tpu.ops.round_kernel_tiled import (
    META_CELL,
    META_COUNT,
    META_SENT,
    META_V,
    _gdt,
    _prec,
    _verdict_block_accepts,
    all_receiver_supported,
    pool_vals_dtype,
)


def build_trial_megakernel(
    cfg: QBAConfig,
    blk_d: int,
    blk_v: int,
    *,
    interpret: bool = False,
    variant: str = "group",
    trial_pack: int = 1,
    out_vma=None,
):
    """Build the one-launch trial kernel.

    Returns ``mega(p_rows, li, li_arg, v_sent, honest_cells, attack,
    rand_v, late) -> (vi', decisions, overflow)`` with

    * ``p_rows`` — bool/int ``[(k,) n_rv, size_l]`` commander P-masks,
    * ``li`` — int32 ``[(k,) n_rv, size_l]`` lieutenant lists,
    * ``li_arg`` — the verdict-table argument (``li`` for the group
      family, :func:`make_verdict_tables` output for ``"allrecv"``),
    * ``v_sent`` — int32 ``[(k,) n_rv]`` per-recipient orders,
    * ``honest_cells`` — int32 ``[(k,) n_pool, 1]``,
    * draws — int32 ``[n_rounds, (k,) n_pool, n_rv]`` mailbox-cell
      ordered, stacked round-major,

    and ``vi'`` int32 ``[(k,) n_rv, w]``, ``decisions`` int32
    ``[(k,) n_rv]``, ``overflow`` bool (per trial when packed).
    """
    n_rv, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv * slots
    n_rounds, n_dis = cfg.n_rounds, cfg.n_dishonest
    kk = trial_pack
    packed = kk > 1
    if kk < 1:
        raise ValueError(f"trial_pack={kk} must be >= 1")
    if n_pool % blk_d:
        raise ValueError(f"blk_d={blk_d} must divide n_pool={n_pool}")
    if n_pool % blk_v:
        raise ValueError(f"blk_v={blk_v} must divide n_pool={n_pool}")
    gdt = _gdt(cfg)
    vdt = pool_vals_dtype(cfg)
    if variant not in ("group", "group-serial", "allrecv"):
        raise ValueError(f"unknown verdict variant {variant!r}")
    if variant == "allrecv" and not all_receiver_supported(size_l, w):
        raise ValueError(
            f"allrecv variant unsupported at size_l={size_l}, w={w}"
        )

    # Receiver lane-packing plan — identical to the fused kernel.
    grp = _lane_group(size_l, n_rv)
    seg_l = grp * size_l
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(*refs):
        if variant == "allrecv":
            (
                p_ref, pt_ref, li_ref, lit_ref, v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                t1_ref, t2_ref, tob_ref, tlh_ref, tlh2_ref,
                ovi_ref, dec_ref, ovf_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs
        else:
            (
                p_ref, pt_ref, li_ref, lit_ref, v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                e_ref, lip_ref, lioob_ref,
                ovi_ref, dec_ref, ovf_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs

        def T(ref, t):  # full per-trial view of a trial-varying ref
            return ref[t] if packed else ref[:]

        iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_rv, w), 1)

        # ---- Entry: step 3a (tfg.py:185-196) + pool compaction.  The
        # consistency predicate on the single-row appended evidence
        # collapses to condition 2 (conditions 1/3 are vacuous at
        # |L'| = 1 — see core/consistent.py); the compaction is
        # pool_from_step3a's exclusive-prefix scatter, expressed as
        # one-hot MXU gathers over the ok lieutenants.
        if packed:
            ovf_ref[:] = jnp.zeros((kk, 1), jnp.int32)
        else:
            ovf_ref[:] = jnp.zeros((1, 1), jnp.int32)
        for t in range(kk):
            p_i = T(p_ref, t)  # [n_rv, size_l] 0/1
            li_m = T(li_ref, t)
            v_col = T(v_ref, t)  # [n_rv, 1]
            # in-tuple mirrors sublist_row: a P position holding a
            # SENTINEL list value stays outside the tuple.
            in_c = (p_i != 0) & (li_m != SENTINEL)
            bad_c = in_c & ((li_m == v_col) | (li_m > w) | (li_m < 0))
            ok_c = (
                jnp.sum(jnp.where(bad_c, 1, 0), axis=1, keepdims=True)
                == 0
            )  # [n_rv, 1]
            vi0 = jnp.where((iota_w == v_col) & ok_c, 1, 0)
            if packed:
                ovi_ref[t] = vi0
            else:
                ovi_ref[:] = vi0

            # The same verdict lane-major (sublane reduce over the
            # transposed operands) for the compaction prefix.
            p_t = T(pt_ref, t)  # [size_l, n_rv]
            li_t = T(lit_ref, t)
            v_row = T(vrow_ref, t)  # [1, n_rv]
            in_r = (p_t != 0) & (li_t != SENTINEL)
            bad_r = in_r & ((li_t == v_row) | (li_t > w) | (li_t < 0))
            ok_r = jnp.where(
                jnp.sum(jnp.where(bad_r, 1, 0), axis=0, keepdims=True)
                == 0,
                1,
                0,
            )  # [1, n_rv]
            x = ok_r
            k = 1
            while k < n_rv:
                x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                k *= 2
            offs_row = x - ok_r  # exclusive prefix = pool position
            total0 = jnp.sum(ok_r)

            d_col = jax.lax.broadcasted_iota(jnp.int32, (n_pool, 1), 0)
            live = d_col < total0  # [n_pool, 1]
            offs_b = jnp.broadcast_to(offs_row, (n_pool, n_rv))
            ok_b = jnp.broadcast_to(ok_r, (n_pool, n_rv))
            onehot = (offs_b <= d_col) & (d_col < offs_b + ok_b)
            oh_i = jnp.where(onehot, 1, 0)
            oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

            def oh_mm(tbl, dt=gdt, oh_f=oh_f):  # [n_rv,X] -> [n_pool,X]
                return jax.lax.dot_general(
                    oh_f.astype(dt), tbl.astype(dt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            # Slot-0 cell: row 0 = the appended own row, rows 1+ empty
            # (append_own on empty evidence).  All gathered values stay
            # <= max(size_l, w) — exact in gdt (see _gdt).
            own = jnp.where(p_i != 0, li_m, SENTINEL)
            own_len = jnp.sum(p_i, axis=1, keepdims=True)
            row0 = jnp.where(
                live, oh_mm(own).astype(jnp.int32), SENTINEL
            ).astype(vdt)
            empty_row = jnp.full((n_pool, size_l), SENTINEL, vdt)
            for r in range(max_l):
                row = row0 if r == 0 else empty_row
                if packed:
                    vals_a[r, t] = row
                else:
                    vals_a[r] = row
            l0 = jnp.where(live, oh_mm(own_len).astype(jnp.int32), 0)
            iota_l = jax.lax.broadcasted_iota(
                jnp.int32, (n_pool, max_l), 1
            )
            lens_v = jnp.where(live & (iota_l == 0), l0, 0)
            p_dec = jnp.where(
                live, oh_mm(p_i).astype(jnp.int32), 0
            ).astype(vdt)
            iota_rv = jax.lax.broadcasted_iota(
                jnp.int32, (n_pool, n_rv), 1
            )
            r_j = jnp.sum(oh_i * iota_rv, axis=1, keepdims=True)
            one_col = jnp.where(live, 1, 0)
            v_dec = jnp.where(live, oh_mm(v_col).astype(jnp.int32), 0)
            meta_v = jnp.concatenate(
                [one_col, v_dec, one_col, jnp.where(live, r_j * slots, 0)],
                axis=1,
            )
            if packed:
                lens_a[t] = lens_v
                pa_scr[t] = p_dec
                meta_a[t] = meta_v
            else:
                lens_a[:] = lens_v
                pa_scr[:] = p_dec
                meta_a[:] = meta_v

        # ---- Round loop: rounds 1..n_dishonest+1 (tfg.py:337) traced
        # ONCE; vi / pools / slot tables never leave VMEM.
        def round_body(r_idx, carry):
            def draws_t(t):
                if packed:
                    return (
                        att_ref[r_idx - 1, t],
                        rv_ref[r_idx - 1, t],
                        late_ref[r_idx - 1, t],
                    )
                return (
                    att_ref[r_idx - 1],
                    rv_ref[r_idx - 1],
                    late_ref[r_idx - 1],
                )

            # --- Verdict (phase A): static sub-block loop, vi carried
            # through ovi — same block-skip + carry as the fused kernel.
            for t in range(kk):
                att_t, rv_t, late_t = draws_t(t)
                if variant == "allrecv":
                    tables_t = (
                        T(t1_ref, t), T(t2_ref, t), T(tob_ref, t),
                        T(tlh_ref, t), T(tlh2_ref, t),
                    )
                else:
                    tables_t = (
                        e_ref[:], T(lip_ref, t), T(lioob_ref, t),
                    )
                for b0 in range(0, n_pool, blk_v):
                    sl = slice(b0, b0 + blk_v)
                    meta_blk = meta_a[t, sl] if packed else meta_a[sl]
                    live = jnp.sum(
                        meta_blk[:, META_SENT : META_SENT + 1]
                    ) > 0

                    @pl.when(live)
                    def _do(t=t, sl=sl, meta_blk=meta_blk,
                            tables_t=tables_t, att_t=att_t, rv_t=rv_t,
                            late_t=late_t):
                        acc, new_vi = _verdict_block_accepts(
                            variant=variant, blk=blk_v, n_rv=n_rv,
                            n_cells=n_pool, slots=slots, max_l=max_l,
                            size_l=size_l, w=w, gdt=gdt, grp=grp,
                            seg_l=seg_l, r0_list=r0_list,
                            r_off=0, r_idx=r_idx,
                            vals=[
                                (
                                    vals_a[r, t, sl] if packed
                                    else vals_a[r, sl]
                                ).astype(jnp.int32)
                                for r in range(max_l)
                            ],
                            lens=(
                                lens_a[t, sl] if packed
                                else lens_a[sl]
                            ),
                            # != 0 re-establishes the 0/1 bound the
                            # KI-3 interval proof needs: scratch reads
                            # are unbounded after the in-kernel round
                            # loop widens, and the decode phase stored
                            # an exact 0/1 mask, so this is free.
                            p_i32=(
                                (
                                    pa_scr[t, sl] if packed
                                    else pa_scr[sl]
                                ) != 0
                            ).astype(jnp.int32),
                            meta=meta_blk,
                            vi=T(ovi_ref, t),
                            honest_col=T(hon_ref, t),
                            att_t=att_t, rv_t=rv_t,
                            late_t=late_t,
                            tables=tables_t,
                            use_fp=cfg.strategy == "split",
                        )
                        if packed:
                            acc_scr[t, sl] = acc
                            ovi_ref[t] = new_vi
                        else:
                            acc_scr[sl] = acc
                            ovi_ref[:] = new_vi

                    @pl.when(jnp.logical_not(live))
                    def _skip_blk(t=t, sl=sl):
                        zeros = jnp.zeros((blk_v, n_rv), jnp.int32)
                        if packed:
                            acc_scr[t, sl] = zeros
                        else:
                            acc_scr[sl] = zeros

            # --- Slot allocation, packet-major (sublane Hillis-Steele
            # prefix); overflow accumulates across rounds (max == any).
            for t in range(kk):
                acc_t = T(acc_scr, t)  # [n_pool, n_rv]
                write0 = (acc_t != 0) & (r_idx <= n_dis)
                w_i = jnp.where(write0, 1, 0)
                x = w_i
                k = 1
                while k < n_pool:
                    x = x + jnp.pad(x, ((k, 0), (0, 0)))[:n_pool, :]
                    k *= 2
                slot0 = x - w_i  # exclusive prefix = outgoing slot
                write_m = write0 & (slot0 < slots)
                ovf_val = jnp.where(
                    jnp.any(write0 & ~write_m), 1, 0
                ).reshape(1, 1)
                if packed:
                    ovf_ref[t : t + 1, :] = jnp.maximum(
                        ovf_ref[t : t + 1, :], ovf_val
                    )
                    w_scr[t] = jnp.where(write_m, 1, 0)
                    s_scr[t] = jnp.minimum(slot0, slots)
                else:
                    ovf_ref[:] = jnp.maximum(ovf_ref[:], ovf_val)
                    w_scr[:] = jnp.where(write_m, 1, 0)
                    s_scr[:] = jnp.minimum(slot0, slots)
                k_lane = jnp.minimum(
                    jnp.sum(w_i, axis=0, keepdims=True), slots
                )  # [1, n_rv]
                x = k_lane
                k = 1
                while k < n_rv:
                    x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                    k *= 2
                offs = x - k_lane  # [1, n_rv] exclusive
                if packed:
                    lane_scr[t, 0:1, :] = offs
                    lane_scr[t, 1:2, :] = k_lane
                else:
                    lane_scr[0:1, :] = offs
                    lane_scr[1:2, :] = k_lane

            # --- Successor pool (phase B) into the B half, static
            # destination-block loop — the fused kernel's _build with
            # the grid step replaced by bd0.
            for t in range(kk):
                att_t, rv_t, late_t = draws_t(t)
                offs = (
                    lane_scr[t, 0:1, :] if packed else lane_scr[0:1, :]
                )
                k_lane = (
                    lane_scr[t, 1:2, :] if packed else lane_scr[1:2, :]
                )
                total = jnp.sum(k_lane)
                for bd0 in range(0, n_pool, blk_d):
                    dsl = slice(bd0, bd0 + blk_d)

                    def zero_outputs(t=t, dsl=dsl):
                        empty = jnp.full((blk_d, size_l), SENTINEL, vdt)
                        for r in range(max_l):
                            if packed:
                                vals_b[r, t, dsl] = empty
                            else:
                                vals_b[r, dsl] = empty
                        zl = jnp.zeros((blk_d, max_l), jnp.int32)
                        zp = jnp.zeros((blk_d, size_l), vdt)
                        zm = jnp.zeros((blk_d, 4), jnp.int32)
                        if packed:
                            lens_b[t, dsl] = zl
                            pb_scr[t, dsl] = zp
                            meta_b[t, dsl] = zm
                        else:
                            lens_b[dsl] = zl
                            pb_scr[dsl] = zp
                            meta_b[dsl] = zm

                    @pl.when(bd0 >= total)
                    def _skip(zero_outputs=zero_outputs):
                        zero_outputs()

                    @pl.when(bd0 < total)
                    def _build(t=t, dsl=dsl, bd0=bd0, offs=offs,
                               k_lane=k_lane, total=total, att_t=att_t,
                               rv_t=rv_t):
                        d_col = bd0 + jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, 1), 0
                        )  # global dst position
                        live = d_col < total  # [blk_d, 1]
                        offs_b = jnp.broadcast_to(offs, (blk_d, n_rv))
                        k_b = jnp.broadcast_to(k_lane, (blk_d, n_rv))
                        onehot = (offs_b <= d_col) & (
                            d_col < offs_b + k_b
                        )
                        oh_i = jnp.where(onehot, 1, 0)
                        iota_rv = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, n_rv), 1
                        )
                        r_j = jnp.sum(
                            oh_i * iota_rv, axis=1, keepdims=True
                        )
                        slot_lane = d_col - jnp.sum(
                            oh_i * offs_b, axis=1, keepdims=True
                        )  # [blk_d, 1]
                        oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

                        def oh_mm(tbl, dt=gdt):  # [n_rv, X]
                            return jax.lax.dot_general(
                                oh_f.astype(dt), tbl.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        def oh_mm_t(tbl, dt=gdt):  # [n_pool, n_rv]
                            return jax.lax.dot_general(
                                oh_f.astype(dt), tbl.astype(dt),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        w_sel = oh_mm_t(T(w_scr, t)) > 0.5
                        s_sel = oh_mm_t(T(s_scr, t)).astype(jnp.int32)
                        g_t = w_sel & (s_sel == slot_lane)
                        g_f = jnp.where(g_t, 1.0, 0.0)

                        def gmm(field, dt=gdt):  # [n_pool, X]
                            return jax.lax.dot_general(
                                g_f.astype(dt), field.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        rows_g = [
                            gmm(
                                vals_a[r, t] if packed else vals_a[r]
                            ).astype(jnp.int32)
                            for r in range(max_l)
                        ]
                        lens_g = gmm(T(lens_a, t)).astype(jnp.int32)
                        p_g = gmm(T(pa_scr, t)).astype(jnp.int32)
                        # f32 + HIGHEST: cell ids reach n_pool-1 > 256.
                        meta_g = gmm(T(meta_a, t), jnp.float32).astype(
                            jnp.int32
                        )
                        cnt_g = meta_g[:, META_COUNT : META_COUNT + 1]
                        v_g = meta_g[:, META_V : META_V + 1]
                        cell_g = meta_g[:, META_CELL : META_CELL + 1]

                        iota_cells = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, n_pool), 1
                        )
                        oh_cell = jnp.where(
                            iota_cells == cell_g, 1.0, 0.0
                        ).astype(gdt)

                        def cell_mm(tbl_t, dt=gdt):  # [n_rv, n_cells]
                            return jax.lax.dot_general(
                                oh_cell.astype(dt), tbl_t.astype(dt),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        def cell_col_mm(tbl, dt=gdt):  # [n_cells, 1]
                            return jax.lax.dot_general(
                                oh_cell.astype(dt), tbl.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        att_rows = cell_mm(att_t)  # [blk_d, n_rv] f32
                        rv_rows = cell_mm(rv_t)
                        att_c = jnp.sum(
                            att_rows * oh_f.astype(jnp.float32),
                            axis=1, keepdims=True,
                        ).astype(jnp.int32)
                        rv_c = jnp.sum(
                            rv_rows * oh_f.astype(jnp.float32),
                            axis=1, keepdims=True,
                        ).astype(jnp.int32)
                        hon_c = cell_col_mm(T(hon_ref, t)).astype(
                            jnp.int32
                        )

                        biz = hon_c == 0
                        clearp_c = biz & ((att_c & CLEAR_P_BIT) != 0)
                        clearl_c = biz & ((att_c & CLEAR_L_BIT) != 0)
                        v2_c = jnp.where(
                            biz & ((att_c & FORGE_BIT) != 0), rv_c, v_g
                        )
                        li_row = oh_mm(T(li_ref, t)).astype(jnp.int32)

                        # Keep/append row algebra — mirrors rebuild_pool.
                        p2 = (p_g != 0) & ~clearp_c
                        if cfg.strategy == "split":
                            # forge-P: statically gated (rebuild_pool).
                            p2 = (
                                biz & ((att_c & FORGE_P_BIT) != 0)
                            ) | p2
                        own = jnp.where(p2, li_row, SENTINEL)
                        own_len = jnp.sum(
                            jnp.where(p2, 1, 0), axis=1, keepdims=True
                        )
                        cnt_eff = jnp.where(clearl_c, 0, cnt_g)
                        dup = jnp.zeros((blk_d, 1), jnp.bool_)
                        for r in range(max_l):
                            mism = jnp.sum(
                                jnp.where(rows_g[r] != own, 1, 0),
                                axis=1, keepdims=True,
                            )
                            dup |= (cnt_g > r) & (mism == 0)
                        dup &= ~clearl_c
                        new_cnt = jnp.where(
                            dup, cnt_eff,
                            jnp.minimum(cnt_eff + 1, max_l),
                        )

                        has = live
                        iota_l = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, max_l), 1
                        )
                        keep_row = iota_l < cnt_eff
                        new_row = ~dup & (iota_l == cnt_eff)
                        olens_val = jnp.where(
                            has,
                            jnp.where(
                                new_row, own_len,
                                jnp.where(keep_row, lens_g, 0),
                            ),
                            0,
                        )
                        if packed:
                            lens_b[t, dsl] = olens_val
                        else:
                            lens_b[dsl] = olens_val
                        for r in range(max_l):
                            keep = ~clearl_c & (r < cnt_eff)
                            is_new = ~dup & (r == cnt_eff)
                            row = jnp.where(
                                is_new, own,
                                jnp.where(keep, rows_g[r], SENTINEL),
                            )
                            row = jnp.where(has, row, SENTINEL).astype(
                                vdt
                            )
                            if packed:
                                vals_b[r, t, dsl] = row
                            else:
                                vals_b[r, dsl] = row
                        op_val = jnp.where(has & p2, 1.0, 0.0).astype(
                            vdt
                        )
                        ometa_val = jnp.where(
                            has,
                            jnp.concatenate(
                                [
                                    new_cnt,
                                    v2_c,
                                    jnp.ones((blk_d, 1), jnp.int32),
                                    r_j * slots + slot_lane,
                                ],
                                axis=1,
                            ),
                            0,
                        )
                        if packed:
                            pb_scr[t, dsl] = op_val
                            meta_b[t, dsl] = ometa_val
                        else:
                            pb_scr[dsl] = op_val
                            meta_b[dsl] = ometa_val

            # --- B half becomes next round's source pool.
            for t in range(kk):
                for r in range(max_l):
                    if packed:
                        vals_a[r, t] = vals_b[r, t]
                    else:
                        vals_a[r] = vals_b[r]
                if packed:
                    lens_a[t] = lens_b[t]
                    pa_scr[t] = pb_scr[t]
                    meta_a[t] = meta_b[t]
                else:
                    lens_a[:] = lens_b[:]
                    pa_scr[:] = pb_scr[:]
                    meta_a[:] = meta_b[:]
            return carry

        jax.lax.fori_loop(1, n_rounds + 1, round_body, jnp.int32(0))

        # ---- Exit: the per-lieutenant decision reduce (decide_order
        # with is_comm=False): min(Vi), sentinel w when Vi is empty.
        for t in range(kk):
            vi_t = T(ovi_ref, t)
            dec_t = jnp.min(
                jnp.where(vi_t != 0, iota_w, w), axis=1, keepdims=True
            )
            if packed:
                dec_ref[t] = dec_t
            else:
                dec_ref[:] = dec_t

    def kdim(*dims):  # prepend the trial-pack axis when packed
        return (kk,) + dims if packed else dims

    n_inputs = 15 if variant == "allrecv" else 13
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(n_inputs)
    ]
    out_specs = tuple(
        pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(3)
    )

    def oshp(*dims, dt=jnp.int32):
        return vma_struct(out_vma, dims, dt)

    pool_scratch = [
        pltpu.VMEM((max_l,) + kdim(n_pool, size_l), vdt),  # vals
        pltpu.VMEM(kdim(n_pool, max_l), jnp.int32),  # lens
        pltpu.VMEM(kdim(n_pool, size_l), vdt),  # p
        pltpu.VMEM(kdim(n_pool, 4), jnp.int32),  # meta
    ]
    call = pl.pallas_call(
        kernel,
        out_shape=(
            oshp(*kdim(n_rv, w)),  # vi'
            oshp(*kdim(n_rv, 1)),  # decisions
            oshp(*((kk, 1) if packed else (1, 1))),  # overflow
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        # No round-scan carries exist to donate — the loop state lives
        # in VMEM scratch (the KI-5 point; analysis/effects._audit_mega
        # proves the scan is gone).  The one legal buffer reuse is the
        # per-recipient order column into the decision column (same
        # shape/dtype; v is only read at the entry decode, decisions
        # are only written after the loop).
        input_output_aliases={4: 1},
        scratch_shapes=pool_scratch + pool_scratch + [
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # acc
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # write mask
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # clamped slots
            pltpu.VMEM(kdim(8, n_rv), jnp.int32),  # offs / k_r rows
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _tail(li_arg):
        if variant == "allrecv":
            return tuple(li_arg)
        if packed:
            li_pack = jnp.stack(
                [
                    li_arg[:, r0 : r0 + grp].reshape(kk, -1)
                    for r0 in r0_list
                ],
                axis=1,
            )  # [kk, len(r0_list), seg_l]
        else:
            li_pack = jnp.stack(
                [li_arg[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
            )
        li_oob_pack = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        return jnp.asarray(e_np), li_pack, li_oob_pack

    def _t(x):  # receiver-major draw layout (per trial when packed)
        return jnp.swapaxes(x, -1, -2)

    def mega(p_rows, li, li_arg, v_sent, honest_pk, attack, rand_v,
             late):
        p_i = p_rows.astype(jnp.int32)
        li_i = li.astype(jnp.int32)
        v_i = v_sent.astype(jnp.int32)
        out = call(
            p_i, _t(p_i), li_i, _t(li_i),
            v_i[..., :, None], v_i[..., None, :], honest_pk,
            _t(attack), _t(rand_v), _t(late), *_tail(li_arg),
        )
        ovi, dec, ovf = out
        if packed:
            return ovi, dec[..., 0], ovf[:, 0] > 0
        return ovi, dec[:, 0], ovf[0, 0] > 0

    return mega
