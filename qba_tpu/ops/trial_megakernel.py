"""One launch = one trial: the round loop, in-kernel.

:func:`build_trial_megakernel` emits a SINGLE ``pallas_call`` whose body

* decodes step 3a on entry (the commander packet consistency verdict +
  compacted-pool build that :func:`qba_tpu.rounds.engine.step3a_one` /
  :func:`qba_tpu.ops.round_kernel_tiled.pool_from_step3a` perform on the
  host for every other engine),
* runs a ``fori_loop`` over all ``n_dishonest + 1`` voting rounds with
  the ``vi`` carry, the ``acc``/slot tables, and BOTH mailbox pools
  (ping-pong A/B) held in VMEM scratch — no HBM round trip between
  rounds, no per-round launch, and
* reduces the per-lieutenant decision (``min(Vi)`` / sentinel ``w``,
  :func:`qba_tpu.core.decide.decide_order`) on exit.

The per-round verdict math is :func:`_verdict_block_accepts` and the
successor-pool build mirrors ``build_fused_round_kernel`` statement for
statement, so the megakernel is bit-identical to the ``pallas_fused``
engine by construction (pinned by tests/test_trial_megakernel.py).  The
entry decode mirrors ``step3a_one``'s ``consistent`` predicate on the
single appended own row (conditions 1/3 are vacuous there) and
``pool_from_step3a``'s prefix-count compaction, as one-hot MXU gathers.

Adversary draws arrive PRE-SAMPLED for all rounds, stacked round-major
(``[n_rounds, (k,) n_cells, n_rv]``): ``jax.random.fold_in`` is value
deterministic, so the host loop that stacks them reproduces exactly the
per-round keys the scanned engines fold in, and the kernel selects a
round's slab by a dynamic index on the leading (majormost) axis.

Trial packing (``trial_pack = k > 1``) folds ``k`` independent trials
into one launch, same layout contract as the packed fused kernel: a
leading ``k`` axis on every trial-varying operand/output/scratch, the
kernel touching only slice ``t`` per trial.

``ProtocolCounters`` are NOT produced here — the loop the counters
wrap no longer exists on the host.  ``rounds/engine.py`` records a
``QBADemotionWarning`` demotion to ``pallas_fused`` when counters are
requested (the ``scan_rounds(collect=True)`` seam).

**In-VMEM generation** (``gen=True``, the ``mega_gen="gf2"`` knob):
the step-1 particle pool is generated INSIDE the same launch — the
packed GF(2) stabilizer tableaux of both protocol circuit families
arrive as static VMEM inputs, the per-trial phase vectors / coins /
correlation mask arrive from :func:`qba_tpu.qsim.protocol_circuits
.stabilizer_gen_operands` (host PRNG, same key tree as
``generate_lists_for``), and the kernel prologue runs ONE batched
measurement sweep — the literal
:func:`qba_tpu.gf2.symplectic.gf2_measure_sweep` both host paths
execute, over per-shot tableaux pre-selected by the qcorr mask — then
decodes order values and derives the ``p``/``li`` operands into VMEM
scratch.  The rest of the kernel body is byte-for-byte the host-gen
body reading those scratch refs, so gen-fused and host-gen trials are
bit-identical by construction and the particle pool never touches HBM.

**Party-sharded variant** (:func:`build_sharded_trial_megakernel`):
the tp-mesh twin — each device carries its ``n_local`` receivers'
verdict/build state, the GLOBAL pool lives in every device's VMEM
scratch, and the per-round pool exchange is PR 14's double-buffered
``make_async_remote_copy`` neighbor ring moved INSIDE the kernel's
round loop (``n_rounds * (tp - 1)`` hops per trial, overlap-scheduled
against the accept algebra).  TPU-only by construction, like
:mod:`qba_tpu.ops.ring_shuffle`; off-TPU the spmd layer runs the
fused-engine schedule as the megakernel's transport twin
(:mod:`qba_tpu.parallel.spmd`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL
from qba_tpu.gf2.symplectic import gf2_measure_sweep
from qba_tpu.ops.round_kernel import (
    CompilerParams,
    _lane_group,
    vma_struct,
)
from qba_tpu.ops.round_kernel_tiled import (
    META_CELL,
    META_COUNT,
    META_SENT,
    META_V,
    _gdt,
    _prec,
    _verdict_block_accepts,
    all_receiver_supported,
    pool_vals_dtype,
)


def build_trial_megakernel(
    cfg: QBAConfig,
    blk_d: int,
    blk_v: int,
    *,
    interpret: bool = False,
    variant: str = "group",
    trial_pack: int = 1,
    out_vma=None,
    gen: bool = False,
):
    """Build the one-launch trial kernel.

    Returns ``mega(p_rows, li, li_arg, v_sent, honest_cells, attack,
    rand_v, late) -> (vi', decisions, overflow)`` with

    * ``p_rows`` — bool/int ``[(k,) n_rv, size_l]`` commander P-masks,
    * ``li`` — int32 ``[(k,) n_rv, size_l]`` lieutenant lists,
    * ``li_arg`` — the verdict-table argument (``li`` for the group
      family, :func:`make_verdict_tables` output for ``"allrecv"``),
    * ``v_sent`` — int32 ``[(k,) n_rv]`` per-recipient orders,
    * ``honest_cells`` — int32 ``[(k,) n_pool, 1]``,
    * draws — int32 ``[n_rounds, (k,) n_pool, n_rv]`` mailbox-cell
      ordered, stacked round-major,

    and ``vi'`` int32 ``[(k,) n_rv, w]``, ``decisions`` int32
    ``[(k,) n_rv]``, ``overflow`` bool (per trial when packed).

    With ``gen=True`` (``mega_gen="gf2"``) the ``p_rows``/``li``/
    ``li_arg`` operands disappear and the returned callable is instead
    ``mega(gen_ops, v_sent, honest_cells, attack, rand_v, late)``
    where ``gen_ops = (qcorr, coins, r_q, r_nq, mflip)`` is exactly
    :func:`~qba_tpu.qsim.protocol_circuits.stabilizer_gen_operands`
    of the trial's ``k_lists`` subkey (leading ``k`` axis when
    packed): the kernel prologue sweeps the tableaux in VMEM and
    derives ``p``/``li``/the verdict tables of the resolved variant
    (lane-packed lists for the group family, the
    :func:`make_receiver_tables` algebra for ``"allrecv"``) into
    scratch.
    """
    n_rv, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv * slots
    n_rounds, n_dis = cfg.n_rounds, cfg.n_dishonest
    kk = trial_pack
    packed = kk > 1
    if kk < 1:
        raise ValueError(f"trial_pack={kk} must be >= 1")
    if n_pool % blk_d:
        raise ValueError(f"blk_d={blk_d} must divide n_pool={n_pool}")
    if n_pool % blk_v:
        raise ValueError(f"blk_v={blk_v} must divide n_pool={n_pool}")
    gdt = _gdt(cfg)
    vdt = pool_vals_dtype(cfg)
    if variant not in ("group", "group-serial", "allrecv"):
        raise ValueError(f"unknown verdict variant {variant!r}")
    if variant == "allrecv" and not all_receiver_supported(size_l, w):
        raise ValueError(
            f"allrecv variant unsupported at size_l={size_l}, w={w}"
        )
    if gen and cfg.qsim_path != "stabilizer":
        raise ValueError(
            "gen-fused megakernel requires qsim_path='stabilizer'"
        )
    n_parties, nq, total = cfg.n_parties, cfg.n_qubits, cfg.total_qubits
    if gen:
        from qba_tpu.qsim.protocol_circuits import stabilizer_gen_tables

        gen_tables = stabilizer_gen_tables(cfg)  # 4 x [2T, W] uint32

    # Receiver lane-packing plan — identical to the fused kernel.
    grp = _lane_group(size_l, n_rv)
    seg_l = grp * size_l
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(*refs):
        if gen and variant == "allrecv":
            (
                xq_ref, zq_ref, xn_ref, zn_ref,
                rq_ref, rn_ref, qc_ref, coins_ref, mf_ref,
                v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                ovi_ref, dec_ref, ovf_ref,
                p_ref, pt_ref, li_ref, lit_ref,
                t1_ref, t2_ref, tob_ref, tlh_ref, tlh2_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs
        elif gen:
            (
                xq_ref, zq_ref, xn_ref, zn_ref,
                rq_ref, rn_ref, qc_ref, coins_ref, mf_ref,
                v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref, e_ref,
                ovi_ref, dec_ref, ovf_ref,
                p_ref, pt_ref, li_ref, lit_ref, lip_ref, lioob_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs
        elif variant == "allrecv":
            (
                p_ref, pt_ref, li_ref, lit_ref, v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                t1_ref, t2_ref, tob_ref, tlh_ref, tlh2_ref,
                ovi_ref, dec_ref, ovf_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs
        else:
            (
                p_ref, pt_ref, li_ref, lit_ref, v_ref, vrow_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                e_ref, lip_ref, lioob_ref,
                ovi_ref, dec_ref, ovf_ref,
                vals_a, lens_a, pa_scr, meta_a,
                vals_b, lens_b, pb_scr, meta_b,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs

        def T(ref, t):  # full per-trial view of a trial-varying ref
            return ref[t] if packed else ref[:]

        if gen:
            # ---- Gen prologue: step 1 IN VMEM.  Select each shot's
            # initial tableau by its qcorr bit (the sweep is per-shot
            # deterministic, so selecting inputs commutes with the host
            # path's post-sweep `where(qcorr, bits_q, bits_nq)`), run
            # the ONE shared measurement sweep over the whole
            # (trial-pack x size_l) shot batch, fold the readout flips,
            # decode order values (measure_to_ints' big-endian weights
            # as shifts), and derive every list-dependent operand the
            # host-gen kernel takes as inputs — into VMEM scratch the
            # rest of the body reads through the SAME names.
            b_all = kk * size_l

            def flat(ref, width):
                val = ref[:]
                return val.reshape(b_all, width) if packed else val

            qc_all = flat(qc_ref, 1)            # [B, 1] int32
            r_all = jnp.where(
                qc_all != 0, flat(rq_ref, 2 * total), flat(rn_ref, 2 * total)
            )
            qc3 = (qc_all != 0)[:, :, None]     # [B, 1, 1]
            xw0 = jnp.where(qc3, xq_ref[:][None], xn_ref[:][None])
            zw0 = jnp.where(qc3, zq_ref[:][None], zn_ref[:][None])
            bits = gf2_measure_sweep(
                total, xw0, zw0, r_all, flat(coins_ref, total)
            ) ^ flat(mf_ref, total)             # [B, T]
            shifts = (nq - 1) - jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, nq), 2
            )
            lists_bt = jnp.sum(
                bits.reshape(b_all, n_parties + 1, nq) << shifts, axis=-1
            )                                   # [B, n_parties + 1]
            for t in range(kk):
                lists_t = lists_bt[t * size_l : (t + 1) * size_l]
                isq = lists_t[:, 0:1] != lists_t[:, 1:2]  # [size_l, 1]
                pt_v = jnp.where(
                    isq & (lists_t[:, 1:2] == T(vrow_ref, t)), 1, 0
                )                               # [size_l, n_rv]
                lit_v = lists_t[:, 2:]
                p_v = jnp.swapaxes(pt_v, 0, 1)
                li_v = jnp.swapaxes(lit_v, 0, 1)
                if packed:
                    p_ref[t], pt_ref[t] = p_v, pt_v
                    li_ref[t], lit_ref[t] = li_v, lit_v
                else:
                    p_ref[:], pt_ref[:] = p_v, pt_v
                    li_ref[:], lit_ref[:] = li_v, lit_v
                if variant == "allrecv":
                    # make_receiver_tables' algebra on the decoded
                    # lists — one-hots built from 3-D iotas instead of
                    # the host's arange-compare + transpose.
                    lit_f = lit_v.astype(jnp.float32)
                    t1_v = lit_f + 1.0
                    t2_v = lit_f * lit_f - 1.0
                    tob_v = jnp.where(
                        (lit_v > w) | (lit_v < 0), 1.0, 0.0
                    )
                    iota_sqn = jax.lax.broadcasted_iota(
                        jnp.int32, (size_l, w, n_rv), 1
                    )
                    tlh_v = jnp.where(
                        lit_v[:, None, :] == iota_sqn, 1.0, 0.0
                    ).reshape(size_l, w * n_rv).astype(gdt)
                    iota_qsn = jax.lax.broadcasted_iota(
                        jnp.int32, (w, size_l, n_rv), 0
                    )
                    tlh2_v = jnp.where(
                        lit_v[None, :, :] == iota_qsn, 1.0, 0.0
                    ).reshape(w * size_l, n_rv).astype(gdt)
                    if packed:
                        t1_ref[t], t2_ref[t], tob_ref[t] = (
                            t1_v, t2_v, tob_v
                        )
                        tlh_ref[t], tlh2_ref[t] = tlh_v, tlh2_v
                    else:
                        t1_ref[:], t2_ref[:], tob_ref[:] = (
                            t1_v, t2_v, tob_v
                        )
                        tlh_ref[:], tlh2_ref[:] = tlh_v, tlh2_v
                else:
                    lip_v = jnp.concatenate(
                        [
                            li_v[r0 : r0 + grp].reshape(1, seg_l)
                            for r0 in r0_list
                        ],
                        axis=0,
                    )
                    lioob_v = jnp.where((lip_v > w) | (lip_v < 0), 1, 0)
                    if packed:
                        lip_ref[t], lioob_ref[t] = lip_v, lioob_v
                    else:
                        lip_ref[:], lioob_ref[:] = lip_v, lioob_v

        iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_rv, w), 1)

        # ---- Entry: step 3a (tfg.py:185-196) + pool compaction.  The
        # consistency predicate on the single-row appended evidence
        # collapses to condition 2 (conditions 1/3 are vacuous at
        # |L'| = 1 — see core/consistent.py); the compaction is
        # pool_from_step3a's exclusive-prefix scatter, expressed as
        # one-hot MXU gathers over the ok lieutenants.
        if packed:
            ovf_ref[:] = jnp.zeros((kk, 1), jnp.int32)
        else:
            ovf_ref[:] = jnp.zeros((1, 1), jnp.int32)
        for t in range(kk):
            p_i = T(p_ref, t)  # [n_rv, size_l] 0/1
            li_m = T(li_ref, t)
            v_col = T(v_ref, t)  # [n_rv, 1]
            # in-tuple mirrors sublist_row: a P position holding a
            # SENTINEL list value stays outside the tuple.
            in_c = (p_i != 0) & (li_m != SENTINEL)
            bad_c = in_c & ((li_m == v_col) | (li_m > w) | (li_m < 0))
            ok_c = (
                jnp.sum(jnp.where(bad_c, 1, 0), axis=1, keepdims=True)
                == 0
            )  # [n_rv, 1]
            vi0 = jnp.where((iota_w == v_col) & ok_c, 1, 0)
            if packed:
                ovi_ref[t] = vi0
            else:
                ovi_ref[:] = vi0

            # The same verdict lane-major (sublane reduce over the
            # transposed operands) for the compaction prefix.
            p_t = T(pt_ref, t)  # [size_l, n_rv]
            li_t = T(lit_ref, t)
            v_row = T(vrow_ref, t)  # [1, n_rv]
            in_r = (p_t != 0) & (li_t != SENTINEL)
            bad_r = in_r & ((li_t == v_row) | (li_t > w) | (li_t < 0))
            ok_r = jnp.where(
                jnp.sum(jnp.where(bad_r, 1, 0), axis=0, keepdims=True)
                == 0,
                1,
                0,
            )  # [1, n_rv]
            x = ok_r
            k = 1
            while k < n_rv:
                x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                k *= 2
            offs_row = x - ok_r  # exclusive prefix = pool position
            total0 = jnp.sum(ok_r)

            d_col = jax.lax.broadcasted_iota(jnp.int32, (n_pool, 1), 0)
            live = d_col < total0  # [n_pool, 1]
            offs_b = jnp.broadcast_to(offs_row, (n_pool, n_rv))
            ok_b = jnp.broadcast_to(ok_r, (n_pool, n_rv))
            onehot = (offs_b <= d_col) & (d_col < offs_b + ok_b)
            oh_i = jnp.where(onehot, 1, 0)
            oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

            def oh_mm(tbl, dt=gdt, oh_f=oh_f):  # [n_rv,X] -> [n_pool,X]
                return jax.lax.dot_general(
                    oh_f.astype(dt), tbl.astype(dt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            # Slot-0 cell: row 0 = the appended own row, rows 1+ empty
            # (append_own on empty evidence).  All gathered values stay
            # <= max(size_l, w) — exact in gdt (see _gdt).
            own = jnp.where(p_i != 0, li_m, SENTINEL)
            own_len = jnp.sum(p_i, axis=1, keepdims=True)
            row0 = jnp.where(
                live, oh_mm(own).astype(jnp.int32), SENTINEL
            ).astype(vdt)
            empty_row = jnp.full((n_pool, size_l), SENTINEL, vdt)
            for r in range(max_l):
                row = row0 if r == 0 else empty_row
                if packed:
                    vals_a[r, t] = row
                else:
                    vals_a[r] = row
            l0 = jnp.where(live, oh_mm(own_len).astype(jnp.int32), 0)
            iota_l = jax.lax.broadcasted_iota(
                jnp.int32, (n_pool, max_l), 1
            )
            lens_v = jnp.where(live & (iota_l == 0), l0, 0)
            p_dec = jnp.where(
                live, oh_mm(p_i).astype(jnp.int32), 0
            ).astype(vdt)
            iota_rv = jax.lax.broadcasted_iota(
                jnp.int32, (n_pool, n_rv), 1
            )
            r_j = jnp.sum(oh_i * iota_rv, axis=1, keepdims=True)
            one_col = jnp.where(live, 1, 0)
            v_dec = jnp.where(live, oh_mm(v_col).astype(jnp.int32), 0)
            meta_v = jnp.concatenate(
                [one_col, v_dec, one_col, jnp.where(live, r_j * slots, 0)],
                axis=1,
            )
            if packed:
                lens_a[t] = lens_v
                pa_scr[t] = p_dec
                meta_a[t] = meta_v
            else:
                lens_a[:] = lens_v
                pa_scr[:] = p_dec
                meta_a[:] = meta_v

        # ---- Round loop: rounds 1..n_dishonest+1 (tfg.py:337) traced
        # ONCE; vi / pools / slot tables never leave VMEM.
        def round_body(r_idx, carry):
            def draws_t(t):
                if packed:
                    return (
                        att_ref[r_idx - 1, t],
                        rv_ref[r_idx - 1, t],
                        late_ref[r_idx - 1, t],
                    )
                return (
                    att_ref[r_idx - 1],
                    rv_ref[r_idx - 1],
                    late_ref[r_idx - 1],
                )

            # --- Verdict (phase A): static sub-block loop, vi carried
            # through ovi — same block-skip + carry as the fused kernel.
            for t in range(kk):
                att_t, rv_t, late_t = draws_t(t)
                if variant == "allrecv":
                    tables_t = (
                        T(t1_ref, t), T(t2_ref, t), T(tob_ref, t),
                        T(tlh_ref, t), T(tlh2_ref, t),
                    )
                else:
                    tables_t = (
                        e_ref[:], T(lip_ref, t), T(lioob_ref, t),
                    )
                for b0 in range(0, n_pool, blk_v):
                    sl = slice(b0, b0 + blk_v)
                    meta_blk = meta_a[t, sl] if packed else meta_a[sl]
                    live = jnp.sum(
                        meta_blk[:, META_SENT : META_SENT + 1]
                    ) > 0

                    @pl.when(live)
                    def _do(t=t, sl=sl, meta_blk=meta_blk,
                            tables_t=tables_t, att_t=att_t, rv_t=rv_t,
                            late_t=late_t):
                        acc, new_vi = _verdict_block_accepts(
                            variant=variant, blk=blk_v, n_rv=n_rv,
                            n_cells=n_pool, slots=slots, max_l=max_l,
                            size_l=size_l, w=w, gdt=gdt, grp=grp,
                            seg_l=seg_l, r0_list=r0_list,
                            r_off=0, r_idx=r_idx,
                            vals=[
                                (
                                    vals_a[r, t, sl] if packed
                                    else vals_a[r, sl]
                                ).astype(jnp.int32)
                                for r in range(max_l)
                            ],
                            lens=(
                                lens_a[t, sl] if packed
                                else lens_a[sl]
                            ),
                            # != 0 re-establishes the 0/1 bound the
                            # KI-3 interval proof needs: scratch reads
                            # are unbounded after the in-kernel round
                            # loop widens, and the decode phase stored
                            # an exact 0/1 mask, so this is free.
                            p_i32=(
                                (
                                    pa_scr[t, sl] if packed
                                    else pa_scr[sl]
                                ) != 0
                            ).astype(jnp.int32),
                            meta=meta_blk,
                            vi=T(ovi_ref, t),
                            honest_col=T(hon_ref, t),
                            att_t=att_t, rv_t=rv_t,
                            late_t=late_t,
                            tables=tables_t,
                            use_fp=cfg.strategy == "split",
                        )
                        if packed:
                            acc_scr[t, sl] = acc
                            ovi_ref[t] = new_vi
                        else:
                            acc_scr[sl] = acc
                            ovi_ref[:] = new_vi

                    @pl.when(jnp.logical_not(live))
                    def _skip_blk(t=t, sl=sl):
                        zeros = jnp.zeros((blk_v, n_rv), jnp.int32)
                        if packed:
                            acc_scr[t, sl] = zeros
                        else:
                            acc_scr[sl] = zeros

            # --- Slot allocation, packet-major (sublane Hillis-Steele
            # prefix); overflow accumulates across rounds (max == any).
            for t in range(kk):
                acc_t = T(acc_scr, t)  # [n_pool, n_rv]
                write0 = (acc_t != 0) & (r_idx <= n_dis)
                w_i = jnp.where(write0, 1, 0)
                x = w_i
                k = 1
                while k < n_pool:
                    x = x + jnp.pad(x, ((k, 0), (0, 0)))[:n_pool, :]
                    k *= 2
                slot0 = x - w_i  # exclusive prefix = outgoing slot
                write_m = write0 & (slot0 < slots)
                ovf_val = jnp.where(
                    jnp.any(write0 & ~write_m), 1, 0
                ).reshape(1, 1)
                if packed:
                    ovf_ref[t : t + 1, :] = jnp.maximum(
                        ovf_ref[t : t + 1, :], ovf_val
                    )
                    w_scr[t] = jnp.where(write_m, 1, 0)
                    s_scr[t] = jnp.minimum(slot0, slots)
                else:
                    ovf_ref[:] = jnp.maximum(ovf_ref[:], ovf_val)
                    w_scr[:] = jnp.where(write_m, 1, 0)
                    s_scr[:] = jnp.minimum(slot0, slots)
                k_lane = jnp.minimum(
                    jnp.sum(w_i, axis=0, keepdims=True), slots
                )  # [1, n_rv]
                x = k_lane
                k = 1
                while k < n_rv:
                    x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                    k *= 2
                offs = x - k_lane  # [1, n_rv] exclusive
                if packed:
                    lane_scr[t, 0:1, :] = offs
                    lane_scr[t, 1:2, :] = k_lane
                else:
                    lane_scr[0:1, :] = offs
                    lane_scr[1:2, :] = k_lane

            # --- Successor pool (phase B) into the B half, static
            # destination-block loop — the fused kernel's _build with
            # the grid step replaced by bd0.
            for t in range(kk):
                att_t, rv_t, late_t = draws_t(t)
                offs = (
                    lane_scr[t, 0:1, :] if packed else lane_scr[0:1, :]
                )
                k_lane = (
                    lane_scr[t, 1:2, :] if packed else lane_scr[1:2, :]
                )
                total = jnp.sum(k_lane)
                for bd0 in range(0, n_pool, blk_d):
                    dsl = slice(bd0, bd0 + blk_d)

                    def zero_outputs(t=t, dsl=dsl):
                        empty = jnp.full((blk_d, size_l), SENTINEL, vdt)
                        for r in range(max_l):
                            if packed:
                                vals_b[r, t, dsl] = empty
                            else:
                                vals_b[r, dsl] = empty
                        zl = jnp.zeros((blk_d, max_l), jnp.int32)
                        zp = jnp.zeros((blk_d, size_l), vdt)
                        zm = jnp.zeros((blk_d, 4), jnp.int32)
                        if packed:
                            lens_b[t, dsl] = zl
                            pb_scr[t, dsl] = zp
                            meta_b[t, dsl] = zm
                        else:
                            lens_b[dsl] = zl
                            pb_scr[dsl] = zp
                            meta_b[dsl] = zm

                    @pl.when(bd0 >= total)
                    def _skip(zero_outputs=zero_outputs):
                        zero_outputs()

                    @pl.when(bd0 < total)
                    def _build(t=t, dsl=dsl, bd0=bd0, offs=offs,
                               k_lane=k_lane, total=total, att_t=att_t,
                               rv_t=rv_t):
                        d_col = bd0 + jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, 1), 0
                        )  # global dst position
                        live = d_col < total  # [blk_d, 1]
                        offs_b = jnp.broadcast_to(offs, (blk_d, n_rv))
                        k_b = jnp.broadcast_to(k_lane, (blk_d, n_rv))
                        onehot = (offs_b <= d_col) & (
                            d_col < offs_b + k_b
                        )
                        oh_i = jnp.where(onehot, 1, 0)
                        iota_rv = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, n_rv), 1
                        )
                        r_j = jnp.sum(
                            oh_i * iota_rv, axis=1, keepdims=True
                        )
                        slot_lane = d_col - jnp.sum(
                            oh_i * offs_b, axis=1, keepdims=True
                        )  # [blk_d, 1]
                        oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

                        def oh_mm(tbl, dt=gdt):  # [n_rv, X]
                            return jax.lax.dot_general(
                                oh_f.astype(dt), tbl.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        def oh_mm_t(tbl, dt=gdt):  # [n_pool, n_rv]
                            return jax.lax.dot_general(
                                oh_f.astype(dt), tbl.astype(dt),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        w_sel = oh_mm_t(T(w_scr, t)) > 0.5
                        s_sel = oh_mm_t(T(s_scr, t)).astype(jnp.int32)
                        g_t = w_sel & (s_sel == slot_lane)
                        g_f = jnp.where(g_t, 1.0, 0.0)

                        def gmm(field, dt=gdt):  # [n_pool, X]
                            return jax.lax.dot_general(
                                g_f.astype(dt), field.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        rows_g = [
                            gmm(
                                vals_a[r, t] if packed else vals_a[r]
                            ).astype(jnp.int32)
                            for r in range(max_l)
                        ]
                        lens_g = gmm(T(lens_a, t)).astype(jnp.int32)
                        p_g = gmm(T(pa_scr, t)).astype(jnp.int32)
                        # f32 + HIGHEST: cell ids reach n_pool-1 > 256.
                        meta_g = gmm(T(meta_a, t), jnp.float32).astype(
                            jnp.int32
                        )
                        cnt_g = meta_g[:, META_COUNT : META_COUNT + 1]
                        v_g = meta_g[:, META_V : META_V + 1]
                        cell_g = meta_g[:, META_CELL : META_CELL + 1]

                        iota_cells = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, n_pool), 1
                        )
                        oh_cell = jnp.where(
                            iota_cells == cell_g, 1.0, 0.0
                        ).astype(gdt)

                        def cell_mm(tbl_t, dt=gdt):  # [n_rv, n_cells]
                            return jax.lax.dot_general(
                                oh_cell.astype(dt), tbl_t.astype(dt),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        def cell_col_mm(tbl, dt=gdt):  # [n_cells, 1]
                            return jax.lax.dot_general(
                                oh_cell.astype(dt), tbl.astype(dt),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(dt),
                            )

                        att_rows = cell_mm(att_t)  # [blk_d, n_rv] f32
                        rv_rows = cell_mm(rv_t)
                        att_c = jnp.sum(
                            att_rows * oh_f.astype(jnp.float32),
                            axis=1, keepdims=True,
                        ).astype(jnp.int32)
                        rv_c = jnp.sum(
                            rv_rows * oh_f.astype(jnp.float32),
                            axis=1, keepdims=True,
                        ).astype(jnp.int32)
                        hon_c = cell_col_mm(T(hon_ref, t)).astype(
                            jnp.int32
                        )

                        biz = hon_c == 0
                        clearp_c = biz & ((att_c & CLEAR_P_BIT) != 0)
                        clearl_c = biz & ((att_c & CLEAR_L_BIT) != 0)
                        v2_c = jnp.where(
                            biz & ((att_c & FORGE_BIT) != 0), rv_c, v_g
                        )
                        li_row = oh_mm(T(li_ref, t)).astype(jnp.int32)

                        # Keep/append row algebra — mirrors rebuild_pool.
                        p2 = (p_g != 0) & ~clearp_c
                        if cfg.strategy == "split":
                            # forge-P: statically gated (rebuild_pool).
                            p2 = (
                                biz & ((att_c & FORGE_P_BIT) != 0)
                            ) | p2
                        own = jnp.where(p2, li_row, SENTINEL)
                        own_len = jnp.sum(
                            jnp.where(p2, 1, 0), axis=1, keepdims=True
                        )
                        cnt_eff = jnp.where(clearl_c, 0, cnt_g)
                        dup = jnp.zeros((blk_d, 1), jnp.bool_)
                        for r in range(max_l):
                            mism = jnp.sum(
                                jnp.where(rows_g[r] != own, 1, 0),
                                axis=1, keepdims=True,
                            )
                            dup |= (cnt_g > r) & (mism == 0)
                        dup &= ~clearl_c
                        new_cnt = jnp.where(
                            dup, cnt_eff,
                            jnp.minimum(cnt_eff + 1, max_l),
                        )

                        has = live
                        iota_l = jax.lax.broadcasted_iota(
                            jnp.int32, (blk_d, max_l), 1
                        )
                        keep_row = iota_l < cnt_eff
                        new_row = ~dup & (iota_l == cnt_eff)
                        olens_val = jnp.where(
                            has,
                            jnp.where(
                                new_row, own_len,
                                jnp.where(keep_row, lens_g, 0),
                            ),
                            0,
                        )
                        if packed:
                            lens_b[t, dsl] = olens_val
                        else:
                            lens_b[dsl] = olens_val
                        for r in range(max_l):
                            keep = ~clearl_c & (r < cnt_eff)
                            is_new = ~dup & (r == cnt_eff)
                            row = jnp.where(
                                is_new, own,
                                jnp.where(keep, rows_g[r], SENTINEL),
                            )
                            row = jnp.where(has, row, SENTINEL).astype(
                                vdt
                            )
                            if packed:
                                vals_b[r, t, dsl] = row
                            else:
                                vals_b[r, dsl] = row
                        op_val = jnp.where(has & p2, 1.0, 0.0).astype(
                            vdt
                        )
                        ometa_val = jnp.where(
                            has,
                            jnp.concatenate(
                                [
                                    new_cnt,
                                    v2_c,
                                    jnp.ones((blk_d, 1), jnp.int32),
                                    r_j * slots + slot_lane,
                                ],
                                axis=1,
                            ),
                            0,
                        )
                        if packed:
                            pb_scr[t, dsl] = op_val
                            meta_b[t, dsl] = ometa_val
                        else:
                            pb_scr[dsl] = op_val
                            meta_b[dsl] = ometa_val

            # --- B half becomes next round's source pool.
            for t in range(kk):
                for r in range(max_l):
                    if packed:
                        vals_a[r, t] = vals_b[r, t]
                    else:
                        vals_a[r] = vals_b[r]
                if packed:
                    lens_a[t] = lens_b[t]
                    pa_scr[t] = pb_scr[t]
                    meta_a[t] = meta_b[t]
                else:
                    lens_a[:] = lens_b[:]
                    pa_scr[:] = pb_scr[:]
                    meta_a[:] = meta_b[:]
            return carry

        jax.lax.fori_loop(1, n_rounds + 1, round_body, jnp.int32(0))

        # ---- Exit: the per-lieutenant decision reduce (decide_order
        # with is_comm=False): min(Vi), sentinel w when Vi is empty.
        for t in range(kk):
            vi_t = T(ovi_ref, t)
            dec_t = jnp.min(
                jnp.where(vi_t != 0, iota_w, w), axis=1, keepdims=True
            )
            if packed:
                dec_ref[t] = dec_t
            else:
                dec_ref[:] = dec_t

    def kdim(*dims):  # prepend the trial-pack axis when packed
        return (kk,) + dims if packed else dims

    if gen:
        n_inputs = 15 if variant == "allrecv" else 16
    else:
        n_inputs = 15 if variant == "allrecv" else 13
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(n_inputs)
    ]
    out_specs = tuple(
        pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(3)
    )

    def oshp(*dims, dt=jnp.int32):
        return vma_struct(out_vma, dims, dt)

    gen_scratch = [
        pltpu.VMEM(kdim(n_rv, size_l), jnp.int32),   # p
        pltpu.VMEM(kdim(size_l, n_rv), jnp.int32),   # pt
        pltpu.VMEM(kdim(n_rv, size_l), jnp.int32),   # li
        pltpu.VMEM(kdim(size_l, n_rv), jnp.int32),   # lit
    ] if gen else []
    if gen and variant == "allrecv":
        gen_scratch += [
            pltpu.VMEM(kdim(size_l, n_rv), jnp.float32),   # t_li1
            pltpu.VMEM(kdim(size_l, n_rv), jnp.float32),   # t_li2
            pltpu.VMEM(kdim(size_l, n_rv), jnp.float32),   # t_oob
            pltpu.VMEM(kdim(size_l, w * n_rv), gdt),       # t_lh
            pltpu.VMEM(kdim(w * size_l, n_rv), gdt),       # t_lh2
        ]
    elif gen:
        gen_scratch += [
            pltpu.VMEM(kdim(len(r0_list), seg_l), jnp.int32),  # lip
            pltpu.VMEM(kdim(len(r0_list), seg_l), jnp.int32),  # lioob
        ]
    pool_scratch = [
        pltpu.VMEM((max_l,) + kdim(n_pool, size_l), vdt),  # vals
        pltpu.VMEM(kdim(n_pool, max_l), jnp.int32),  # lens
        pltpu.VMEM(kdim(n_pool, size_l), vdt),  # p
        pltpu.VMEM(kdim(n_pool, 4), jnp.int32),  # meta
    ]
    call = pl.pallas_call(
        kernel,
        out_shape=(
            oshp(*kdim(n_rv, w)),  # vi'
            oshp(*kdim(n_rv, 1)),  # decisions
            oshp(*((kk, 1) if packed else (1, 1))),  # overflow
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        # No round-scan carries exist to donate — the loop state lives
        # in VMEM scratch (the KI-5 point; analysis/effects._audit_mega
        # proves the scan is gone).  The one legal buffer reuse is the
        # per-recipient order column into the decision column (same
        # shape/dtype; v is only read at the entry decode, decisions
        # are only written after the loop).
        input_output_aliases={9: 1} if gen else {4: 1},
        scratch_shapes=gen_scratch + pool_scratch + pool_scratch + [
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # acc
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # write mask
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # clamped slots
            pltpu.VMEM(kdim(8, n_rv), jnp.int32),  # offs / k_r rows
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _tail(li_arg):
        if variant == "allrecv":
            return tuple(li_arg)
        if packed:
            li_pack = jnp.stack(
                [
                    li_arg[:, r0 : r0 + grp].reshape(kk, -1)
                    for r0 in r0_list
                ],
                axis=1,
            )  # [kk, len(r0_list), seg_l]
        else:
            li_pack = jnp.stack(
                [li_arg[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
            )
        li_oob_pack = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        return jnp.asarray(e_np), li_pack, li_oob_pack

    def _t(x):  # receiver-major draw layout (per trial when packed)
        return jnp.swapaxes(x, -1, -2)

    def _unwrap(out):
        ovi, dec, ovf = out
        if packed:
            return ovi, dec[..., 0], ovf[:, 0] > 0
        return ovi, dec[:, 0], ovf[0, 0] > 0

    if gen:
        tables_c = tuple(jnp.asarray(tbl) for tbl in gen_tables)

        gen_tail = () if variant == "allrecv" else (jnp.asarray(e_np),)

        def mega_gen(gen_ops, v_sent, honest_pk, attack, rand_v, late):
            qcorr, coins, r_q, r_nq, mflip = gen_ops
            v_i = v_sent.astype(jnp.int32)
            return _unwrap(call(
                *tables_c,
                r_q.astype(jnp.int32), r_nq.astype(jnp.int32),
                qcorr.astype(jnp.int32)[..., None],
                coins.astype(jnp.int32), mflip.astype(jnp.int32),
                v_i[..., :, None], v_i[..., None, :], honest_pk,
                _t(attack), _t(rand_v), _t(late), *gen_tail,
            ))

        return mega_gen

    def mega(p_rows, li, li_arg, v_sent, honest_pk, attack, rand_v,
             late):
        p_i = p_rows.astype(jnp.int32)
        li_i = li.astype(jnp.int32)
        v_i = v_sent.astype(jnp.int32)
        return _unwrap(call(
            p_i, _t(p_i), li_i, _t(li_i),
            v_i[..., :, None], v_i[..., None, :], honest_pk,
            _t(attack), _t(rand_v), _t(late), *_tail(li_arg),
        ))

    return mega


def _ring_compiler_params(collective_id: int):
    """Mosaic params for the in-loop ring: side-effecting (remote DMA
    must not be reordered or elided) + a collective id distinct from
    the per-round ring shuffle's.  Older jax builds predate the
    ``has_side_effects`` field — there the DMA effects themselves keep
    the call live, so dropping the flag is trace-compatible (those
    builds cannot execute remote DMA anyway; this kernel is TPU-only
    and the off-TPU suites only trace it)."""
    try:
        return CompilerParams(
            has_side_effects=True,
            collective_id=collective_id,
            vmem_limit_bytes=100 * 2**20,
        )
    except TypeError:
        return CompilerParams(
            collective_id=collective_id,
            vmem_limit_bytes=100 * 2**20,
        )


def build_sharded_trial_megakernel(
    cfg: QBAConfig,
    blk_d: int,
    blk_v: int,
    *,
    n_tp: int,
    variant: str = "group",
    out_vma=None,
    axis_name: str = "tp",
    mesh_axes: tuple[str, ...] = ("dp", "tp"),
    collective_id: int = 2,
):
    """One launch = one trial on a ``tp``-sharded mesh: the megakernel
    with the per-round pool exchange — PR 14's double-buffered
    ``make_async_remote_copy`` neighbor ring — INSIDE the round
    ``fori_loop``.

    Each device carries its ``n_local = n_lieutenants / n_tp``
    receivers' state: the verdict carry ``vi`` [n_local, w], the LOCAL
    successor-pool half B (``n_local * slots`` rows, locally
    compacted), and ONE assembled GLOBAL pool half A (``n_pool`` rows)
    every shard reads during the verdict phase.  A round is

    1. ``exchange()`` — neighbor barrier, own B segment into A at this
       shard's offset, then ``n_tp - 1`` remote-DMA hops (one per pool
       leaf: vals/lens/p/meta through 2-slot comm scratch, the
       :mod:`qba_tpu.ops.ring_shuffle` schedule verbatim) depositing
       every other shard's segment at its owner's offset — so ring
       hops per trial = ``n_rounds * (n_tp - 1)``, the count the KI-5
       launch model pins;
    2. the single-device round body at ``n_rv = n_local`` with
       ``r_off = start`` (the traced global receiver offset
       ``axis_index("tp") * n_local`` — sender/self-delivery ids stay
       global) over the global A, writing the local B.

    Pool cell ids in ``meta`` are GLOBAL (``(start + r_j) * slots +
    slot``), so draw selection and the sender-id algebra are
    bit-identical to the single-device megakernel; physical rows are
    segment-compacted rather than globally compacted, which the
    verdict phase is insensitive to (empty rows carry ``SENT = 0`` —
    the same layout the fused sharded engine's host-side gather
    produces, pinned bit-identical in tests/test_parallel.py).

    The cross-exchange barrier re-runs EVERY exchange (not just at
    kernel entry like the one-launch-per-hop ring shuffle): a neighbor
    must not start a new exchange's remote writes into our comm slots
    while this device still reads the prior exchange's deposits.  The
    pairwise 2-signal barrier bounds ring skew to one exchange, which
    is exactly the guarantee the 2-slot buffers need.

    TPU-only by construction (remote DMA has no interpret path):
    :mod:`qba_tpu.parallel.spmd` builds it only on a real TPU backend
    and runs the fused-engine schedule as the off-TPU transport twin.

    Returns ``mega(my_p, my_li, my_v, honest_cells, attack, rand_v,
    late) -> (vi' [n_local, w], decisions [n_local], overflow)`` with
    ``my_*`` the shard's receiver slices and draws ``[n_rounds,
    n_pool, n_local]`` cell-major (this shard's receiver columns of
    the full stacked slabs).
    """
    n_rv, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv * slots
    n_rounds, n_dis = cfg.n_rounds, cfg.n_dishonest
    if n_tp < 2:
        raise ValueError(f"n_tp={n_tp} must be >= 2")
    if n_rv % n_tp:
        raise ValueError(
            f"n_tp={n_tp} must divide n_lieutenants={n_rv}"
        )
    if axis_name not in mesh_axes:
        raise ValueError(
            f"axis_name {axis_name!r} not in mesh_axes {mesh_axes!r}"
        )
    n_local = n_rv // n_tp
    loc_rows = n_local * slots
    if loc_rows % blk_d:
        raise ValueError(
            f"blk_d={blk_d} must divide local rows {loc_rows}"
        )
    if n_pool % blk_v:
        raise ValueError(f"blk_v={blk_v} must divide n_pool={n_pool}")
    if variant not in ("group", "group-serial"):
        raise ValueError(
            "party-sharded megakernel stays in the group family; got "
            f"variant={variant!r}"
        )
    gdt = _gdt(cfg)
    vdt = pool_vals_dtype(cfg)

    # Receiver lane-packing plan at the LOCAL receiver count.
    grp = _lane_group(size_l, n_local)
    seg_l = grp * size_l
    r0_list = list(range(0, n_local - grp + 1, grp))
    if n_local % grp:
        r0_list.append(n_local - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(
        p_ref, pt_ref, li_ref, lit_ref, v_ref, vrow_ref,
        hon_ref, att_ref, rv_ref, late_ref,
        e_ref, lip_ref, lioob_ref,
        ovi_ref, dec_ref, ovf_ref,
        vals_a, lens_a, pa_scr, meta_a,
        vals_b, lens_b, pb_scr, meta_b,
        acc_scr,
        vals_c, lens_c, p_c, meta_c, send_sem, recv_sem,
    ):
        my_tp = jax.lax.axis_index(axis_name)
        start = my_tp * n_local  # global receiver offset (traced)

        def coords(tp_idx):
            # Mesh-coordinate device id: every non-tp axis keeps this
            # device's own index (the ring never leaves its tp row).
            return tuple(
                tp_idx if a == axis_name else jax.lax.axis_index(a)
                for a in mesh_axes
            )

        right = jax.lax.rem(my_tp + 1, n_tp)
        left = jax.lax.rem(my_tp + n_tp - 1, n_tp)

        iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_local, w), 1)

        # ---- Entry: step 3a on the LOCAL receivers + local-segment
        # compaction into the B half (the global A is assembled by the
        # first exchange).  Same algebra as the single-device entry
        # decode with n_rv -> n_local; cell ids written GLOBAL.
        ovf_ref[:] = jnp.zeros((1, 1), jnp.int32)
        p_i = p_ref[:]  # [n_local, size_l] 0/1
        li_m = li_ref[:]
        v_col = v_ref[:]  # [n_local, 1]
        in_c = (p_i != 0) & (li_m != SENTINEL)
        bad_c = in_c & ((li_m == v_col) | (li_m > w) | (li_m < 0))
        ok_c = (
            jnp.sum(jnp.where(bad_c, 1, 0), axis=1, keepdims=True) == 0
        )
        ovi_ref[:] = jnp.where((iota_w == v_col) & ok_c, 1, 0)

        p_t = pt_ref[:]  # [size_l, n_local]
        li_t = lit_ref[:]
        v_row = vrow_ref[:]  # [1, n_local]
        in_r = (p_t != 0) & (li_t != SENTINEL)
        bad_r = in_r & ((li_t == v_row) | (li_t > w) | (li_t < 0))
        ok_r = jnp.where(
            jnp.sum(jnp.where(bad_r, 1, 0), axis=0, keepdims=True) == 0,
            1,
            0,
        )  # [1, n_local]
        x = ok_r
        k = 1
        while k < n_local:
            x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_local]
            k *= 2
        offs_row = x - ok_r  # exclusive prefix = local pool position
        total0 = jnp.sum(ok_r)

        d_col = jax.lax.broadcasted_iota(jnp.int32, (loc_rows, 1), 0)
        live0 = d_col < total0
        offs_b = jnp.broadcast_to(offs_row, (loc_rows, n_local))
        ok_b = jnp.broadcast_to(ok_r, (loc_rows, n_local))
        onehot0 = (offs_b <= d_col) & (d_col < offs_b + ok_b)
        oh_i0 = jnp.where(onehot0, 1, 0)
        oh_f0 = jnp.where(onehot0, 1.0, 0.0).astype(gdt)

        def oh_mm0(tbl, dt=gdt):  # [n_local, X] -> [loc_rows, X]
            return jax.lax.dot_general(
                oh_f0.astype(dt), tbl.astype(dt),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(dt),
            )

        own0 = jnp.where(p_i != 0, li_m, SENTINEL)
        own_len0 = jnp.sum(p_i, axis=1, keepdims=True)
        row0 = jnp.where(
            live0, oh_mm0(own0).astype(jnp.int32), SENTINEL
        ).astype(vdt)
        empty0 = jnp.full((loc_rows, size_l), SENTINEL, vdt)
        for r in range(max_l):
            vals_b[r] = row0 if r == 0 else empty0
        l0 = jnp.where(live0, oh_mm0(own_len0).astype(jnp.int32), 0)
        iota_l0 = jax.lax.broadcasted_iota(
            jnp.int32, (loc_rows, max_l), 1
        )
        lens_b[:] = jnp.where(live0 & (iota_l0 == 0), l0, 0)
        pb_scr[:] = jnp.where(
            live0, oh_mm0(p_i).astype(jnp.int32), 0
        ).astype(vdt)
        iota_rv0 = jax.lax.broadcasted_iota(
            jnp.int32, (loc_rows, n_local), 1
        )
        r_j0 = jnp.sum(oh_i0 * iota_rv0, axis=1, keepdims=True)
        one_col0 = jnp.where(live0, 1, 0)
        v_dec0 = jnp.where(live0, oh_mm0(v_col).astype(jnp.int32), 0)
        meta_b[:] = jnp.concatenate(
            [
                one_col0, v_dec0, one_col0,
                jnp.where(live0, (start + r_j0) * slots, 0),
            ],
            axis=1,
        )

        # ---- In-loop exchange: assemble global A from every shard's
        # B segment.  The ring_shuffle hop schedule, once per pool
        # leaf, all four leaves' hops issued before any wait.
        def exchange():
            barrier = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=coords(left),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=coords(right),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            pltpu.semaphore_wait(barrier, 2)

            row_own = my_tp * loc_rows
            for r in range(max_l):
                vals_a[r, pl.ds(row_own, loc_rows)] = vals_b[r]
                vals_c[0, r] = vals_b[r]
            lens_a[pl.ds(row_own, loc_rows)] = lens_b[:]
            lens_c[0] = lens_b[:]
            pa_scr[pl.ds(row_own, loc_rows)] = pb_scr[:]
            p_c[0] = pb_scr[:]
            meta_a[pl.ds(row_own, loc_rows)] = meta_b[:]
            meta_c[0] = meta_b[:]

            leaves = (vals_c, lens_c, p_c, meta_c)
            for step in range(n_tp - 1):
                send_slot = step % 2
                recv_slot = (step + 1) % 2
                rdmas = []
                for leaf, ref in enumerate(leaves):
                    rdma = pltpu.make_async_remote_copy(
                        src_ref=ref.at[send_slot],
                        dst_ref=ref.at[recv_slot],
                        send_sem=send_sem.at[leaf, send_slot],
                        recv_sem=recv_sem.at[leaf, recv_slot],
                        device_id=coords(right),
                        device_id_type=pltpu.DeviceIdType.MESH,
                    )
                    rdma.start()
                    rdmas.append(rdma)
                for rdma in rdmas:
                    rdma.wait()
                # The segment now in recv_slot originated step+1 hops
                # to the left.
                src_dev = jax.lax.rem(my_tp + n_tp - step - 1, n_tp)
                dst0 = src_dev * loc_rows
                for r in range(max_l):
                    vals_a[r, pl.ds(dst0, loc_rows)] = (
                        vals_c[recv_slot, r]
                    )
                lens_a[pl.ds(dst0, loc_rows)] = lens_c[recv_slot]
                pa_scr[pl.ds(dst0, loc_rows)] = p_c[recv_slot]
                meta_a[pl.ds(dst0, loc_rows)] = meta_c[recv_slot]

        # ---- Round loop: exchange, verdict over the global A at the
        # local receiver lanes, local B rebuild.
        def round_body(r_idx, carry):
            exchange()
            att_t = att_ref[r_idx - 1]  # [n_local, n_pool]
            rv_t = rv_ref[r_idx - 1]
            late_t = late_ref[r_idx - 1]
            tables_t = (e_ref[:], lip_ref[:], lioob_ref[:])

            # --- Verdict (phase A), vi carried through ovi.
            for b0 in range(0, n_pool, blk_v):
                sl = slice(b0, b0 + blk_v)
                meta_blk = meta_a[sl]
                live_b = jnp.sum(
                    meta_blk[:, META_SENT : META_SENT + 1]
                ) > 0

                @pl.when(live_b)
                def _do(sl=sl, meta_blk=meta_blk, att_t=att_t,
                        rv_t=rv_t, late_t=late_t, tables_t=tables_t):
                    acc, new_vi = _verdict_block_accepts(
                        variant=variant, blk=blk_v, n_rv=n_local,
                        n_cells=n_pool, slots=slots, max_l=max_l,
                        size_l=size_l, w=w, gdt=gdt, grp=grp,
                        seg_l=seg_l, r0_list=r0_list,
                        r_off=start, r_idx=r_idx,
                        vals=[
                            vals_a[r, sl].astype(jnp.int32)
                            for r in range(max_l)
                        ],
                        lens=lens_a[sl],
                        p_i32=(pa_scr[sl] != 0).astype(jnp.int32),
                        meta=meta_blk,
                        vi=ovi_ref[:],
                        honest_col=hon_ref[:],
                        att_t=att_t, rv_t=rv_t, late_t=late_t,
                        tables=tables_t,
                        use_fp=cfg.strategy == "split",
                    )
                    acc_scr[sl] = acc
                    ovi_ref[:] = new_vi

                @pl.when(jnp.logical_not(live_b))
                def _skip_blk(sl=sl):
                    acc_scr[sl] = jnp.zeros(
                        (blk_v, n_local), jnp.int32
                    )

            # --- Slot allocation: packet-major prefix over the GLOBAL
            # pool, lane prefix over the LOCAL receivers.
            acc_t = acc_scr[:]  # [n_pool, n_local]
            write0 = (acc_t != 0) & (r_idx <= n_dis)
            w_i = jnp.where(write0, 1, 0)
            x = w_i
            k = 1
            while k < n_pool:
                x = x + jnp.pad(x, ((k, 0), (0, 0)))[:n_pool, :]
                k *= 2
            slot0 = x - w_i  # exclusive prefix = outgoing slot
            write_m = write0 & (slot0 < slots)
            ovf_val = jnp.where(
                jnp.any(write0 & ~write_m), 1, 0
            ).reshape(1, 1)
            ovf_ref[:] = jnp.maximum(ovf_ref[:], ovf_val)
            w_m = jnp.where(write_m, 1, 0)  # [n_pool, n_local]
            s_m = jnp.minimum(slot0, slots)
            k_lane = jnp.minimum(
                jnp.sum(w_i, axis=0, keepdims=True), slots
            )  # [1, n_local]
            x = k_lane
            k = 1
            while k < n_local:
                x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_local]
                k *= 2
            offs = x - k_lane  # [1, n_local] exclusive
            total = jnp.sum(k_lane)

            # --- Successor pool (phase B) into the local B half.
            for bd0 in range(0, loc_rows, blk_d):
                dsl = slice(bd0, bd0 + blk_d)

                def zero_outputs(dsl=dsl):
                    empty = jnp.full((blk_d, size_l), SENTINEL, vdt)
                    for r in range(max_l):
                        vals_b[r, dsl] = empty
                    lens_b[dsl] = jnp.zeros((blk_d, max_l), jnp.int32)
                    pb_scr[dsl] = jnp.zeros((blk_d, size_l), vdt)
                    meta_b[dsl] = jnp.zeros((blk_d, 4), jnp.int32)

                @pl.when(bd0 >= total)
                def _skip(zero_outputs=zero_outputs):
                    zero_outputs()

                @pl.when(bd0 < total)
                def _build(dsl=dsl, bd0=bd0, offs=offs, k_lane=k_lane,
                           total=total, w_m=w_m, s_m=s_m, att_t=att_t,
                           rv_t=rv_t):
                    d_col = bd0 + jax.lax.broadcasted_iota(
                        jnp.int32, (blk_d, 1), 0
                    )  # LOCAL dst position
                    live = d_col < total  # [blk_d, 1]
                    offs_b = jnp.broadcast_to(offs, (blk_d, n_local))
                    k_b = jnp.broadcast_to(k_lane, (blk_d, n_local))
                    onehot = (offs_b <= d_col) & (
                        d_col < offs_b + k_b
                    )
                    oh_i = jnp.where(onehot, 1, 0)
                    iota_rv = jax.lax.broadcasted_iota(
                        jnp.int32, (blk_d, n_local), 1
                    )
                    r_j = jnp.sum(
                        oh_i * iota_rv, axis=1, keepdims=True
                    )  # LOCAL receiver index
                    slot_lane = d_col - jnp.sum(
                        oh_i * offs_b, axis=1, keepdims=True
                    )
                    oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

                    def oh_mm(tbl, dt=gdt):  # [n_local, X]
                        return jax.lax.dot_general(
                            oh_f.astype(dt), tbl.astype(dt),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_prec(dt),
                        )

                    def oh_mm_t(tbl, dt=gdt):  # [n_pool, n_local]
                        return jax.lax.dot_general(
                            oh_f.astype(dt), tbl.astype(dt),
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_prec(dt),
                        )

                    w_sel = oh_mm_t(w_m) > 0.5
                    s_sel = oh_mm_t(s_m).astype(jnp.int32)
                    g_t = w_sel & (s_sel == slot_lane)
                    g_f = jnp.where(g_t, 1.0, 0.0)

                    def gmm(field, dt=gdt):  # [n_pool, X] global A
                        return jax.lax.dot_general(
                            g_f.astype(dt), field.astype(dt),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_prec(dt),
                        )

                    rows_g = [
                        gmm(vals_a[r]).astype(jnp.int32)
                        for r in range(max_l)
                    ]
                    lens_g = gmm(lens_a[:]).astype(jnp.int32)
                    p_g = gmm(pa_scr[:]).astype(jnp.int32)
                    # f32 + HIGHEST: cell ids reach n_pool-1 > 256.
                    meta_g = gmm(meta_a[:], jnp.float32).astype(
                        jnp.int32
                    )
                    cnt_g = meta_g[:, META_COUNT : META_COUNT + 1]
                    v_g = meta_g[:, META_V : META_V + 1]
                    cell_g = meta_g[:, META_CELL : META_CELL + 1]

                    iota_cells = jax.lax.broadcasted_iota(
                        jnp.int32, (blk_d, n_pool), 1
                    )
                    oh_cell = jnp.where(
                        iota_cells == cell_g, 1.0, 0.0
                    ).astype(gdt)

                    def cell_mm(tbl_t, dt=gdt):  # [n_local, n_pool]
                        return jax.lax.dot_general(
                            oh_cell.astype(dt), tbl_t.astype(dt),
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_prec(dt),
                        )

                    def cell_col_mm(tbl, dt=gdt):  # [n_pool, 1]
                        return jax.lax.dot_general(
                            oh_cell.astype(dt), tbl.astype(dt),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_prec(dt),
                        )

                    att_rows = cell_mm(att_t)  # [blk_d, n_local]
                    rv_rows = cell_mm(rv_t)
                    att_c = jnp.sum(
                        att_rows * oh_f.astype(jnp.float32),
                        axis=1, keepdims=True,
                    ).astype(jnp.int32)
                    rv_c = jnp.sum(
                        rv_rows * oh_f.astype(jnp.float32),
                        axis=1, keepdims=True,
                    ).astype(jnp.int32)
                    hon_c = cell_col_mm(hon_ref[:]).astype(jnp.int32)

                    biz = hon_c == 0
                    clearp_c = biz & ((att_c & CLEAR_P_BIT) != 0)
                    clearl_c = biz & ((att_c & CLEAR_L_BIT) != 0)
                    v2_c = jnp.where(
                        biz & ((att_c & FORGE_BIT) != 0), rv_c, v_g
                    )
                    li_row = oh_mm(li_ref[:]).astype(jnp.int32)

                    # Keep/append row algebra — mirrors rebuild_pool.
                    p2 = (p_g != 0) & ~clearp_c
                    if cfg.strategy == "split":
                        p2 = (
                            biz & ((att_c & FORGE_P_BIT) != 0)
                        ) | p2
                    own = jnp.where(p2, li_row, SENTINEL)
                    own_len = jnp.sum(
                        jnp.where(p2, 1, 0), axis=1, keepdims=True
                    )
                    cnt_eff = jnp.where(clearl_c, 0, cnt_g)
                    dup = jnp.zeros((blk_d, 1), jnp.bool_)
                    for r in range(max_l):
                        mism = jnp.sum(
                            jnp.where(rows_g[r] != own, 1, 0),
                            axis=1, keepdims=True,
                        )
                        dup |= (cnt_g > r) & (mism == 0)
                    dup &= ~clearl_c
                    new_cnt = jnp.where(
                        dup, cnt_eff,
                        jnp.minimum(cnt_eff + 1, max_l),
                    )

                    has = live
                    iota_l = jax.lax.broadcasted_iota(
                        jnp.int32, (blk_d, max_l), 1
                    )
                    keep_row = iota_l < cnt_eff
                    new_row = ~dup & (iota_l == cnt_eff)
                    lens_b[dsl] = jnp.where(
                        has,
                        jnp.where(
                            new_row, own_len,
                            jnp.where(keep_row, lens_g, 0),
                        ),
                        0,
                    )
                    for r in range(max_l):
                        keep = ~clearl_c & (r < cnt_eff)
                        is_new = ~dup & (r == cnt_eff)
                        row = jnp.where(
                            is_new, own,
                            jnp.where(keep, rows_g[r], SENTINEL),
                        )
                        vals_b[r, dsl] = jnp.where(
                            has, row, SENTINEL
                        ).astype(vdt)
                    pb_scr[dsl] = jnp.where(
                        has & p2, 1.0, 0.0
                    ).astype(vdt)
                    meta_b[dsl] = jnp.where(
                        has,
                        jnp.concatenate(
                            [
                                new_cnt,
                                v2_c,
                                jnp.ones((blk_d, 1), jnp.int32),
                                # GLOBAL cell id: r_j is local.
                                (start + r_j) * slots + slot_lane,
                            ],
                            axis=1,
                        ),
                        0,
                    )
            return carry

        jax.lax.fori_loop(1, n_rounds + 1, round_body, jnp.int32(0))

        # ---- Exit: the per-lieutenant decision reduce.
        dec_ref[:] = jnp.min(
            jnp.where(ovi_ref[:] != 0, iota_w, w),
            axis=1, keepdims=True,
        )

    def oshp(*dims, dt=jnp.int32):
        return vma_struct(out_vma, dims, dt)

    call = pl.pallas_call(
        kernel,
        out_shape=(
            oshp(n_local, w),  # vi'
            oshp(n_local, 1),  # decisions
            oshp(1, 1),  # overflow
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(13)
        ],
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(3)
        ),
        input_output_aliases={4: 1},
        scratch_shapes=[
            # Global A half: every shard's assembled pool.
            pltpu.VMEM((max_l, n_pool, size_l), vdt),
            pltpu.VMEM((n_pool, max_l), jnp.int32),
            pltpu.VMEM((n_pool, size_l), vdt),
            pltpu.VMEM((n_pool, 4), jnp.int32),
            # Local B half: this shard's successor segment.
            pltpu.VMEM((max_l, loc_rows, size_l), vdt),
            pltpu.VMEM((loc_rows, max_l), jnp.int32),
            pltpu.VMEM((loc_rows, size_l), vdt),
            pltpu.VMEM((loc_rows, 4), jnp.int32),
            pltpu.VMEM((n_pool, n_local), jnp.int32),  # acc
            # 2-slot ring comm buffers, one per pool leaf.
            pltpu.VMEM((2, max_l, loc_rows, size_l), vdt),
            pltpu.VMEM((2, loc_rows, max_l), jnp.int32),
            pltpu.VMEM((2, loc_rows, size_l), vdt),
            pltpu.VMEM((2, loc_rows, 4), jnp.int32),
            pltpu.SemaphoreType.DMA((4, 2)),
            pltpu.SemaphoreType.DMA((4, 2)),
        ],
        compiler_params=_ring_compiler_params(collective_id),
    )

    def _t(x):  # receiver-major draw layout
        return jnp.swapaxes(x, -1, -2)

    def mega(my_p, my_li, my_v, honest_pk, attack, rand_v, late):
        p_i = my_p.astype(jnp.int32)
        li_i = my_li.astype(jnp.int32)
        v_i = my_v.astype(jnp.int32)
        li_pack = jnp.stack(
            [li_i[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
        )
        li_oob = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        ovi, dec, ovf = call(
            p_i, _t(p_i), li_i, _t(li_i),
            v_i[:, None], v_i[None, :], honest_pk,
            _t(attack), _t(rand_v), _t(late),
            jnp.asarray(e_np), li_pack, li_oob,
        )
        return ovi, dec[:, 0], ovf[0, 0] > 0

    return mega
